"""Single-device CG solvers as compiled XLA programs.

The reference implements four execution models of CG (host/device x
classic/pipelined, ``acg/cgcuda.c``, ``acg/cg-kernels-cuda.cu``).  On TPU
these collapse into compiled whole-solve programs (SURVEY.md section 7):
XLA's execution model *is* the reference's monolithic persistent-kernel
variant (``acgsolvercuda_cg_kernel``, ``cg-kernels-cuda.cu:627-970``) --
one program per solve, `lax.while_loop` for the iteration, scalars resident
on device (the reference keeps alpha/beta/||r||^2 in device memory for the
same reason, ``cgcuda.c:465-486``), and the convergence test a device-side
predicate (``cg-kernels-cuda.cu:948-957``).

Two algorithms:

* :func:`solve_cg` -- classic CG (`acgsolver_solve` recurrences).
* :func:`solve_cg_pipelined` -- Ghysels-Vanroose pipelined CG with the
  fused 6-vector update of the reference's cooperative kernel
  (``cg-kernels-cuda.cu:187-269``): beta = gamma/gamma_prev, alpha =
  gamma/(delta - beta*gamma/alpha_prev), z=q+beta z, t=w+beta t, p=r+beta p,
  x+=alpha p, r-=alpha t, w-=alpha z, with gamma_prev=alpha_prev=inf on the
  first iteration (``cgcuda.c:1553-1560``).  On a single chip the pipelined
  variant exists for parity and numerics; its payoff (one fused allreduce)
  appears on the mesh.
"""

from __future__ import annotations

import dataclasses
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from acg_tpu.errors import AcgError, ErrorCode, NotConvergedError
from acg_tpu.ops.precision import dot2
from acg_tpu.ops.spmv import (DeviceMatrix, DiaMatrix, acc_dtype,
                              matrix_dtype, matrix_index_bytes, spmv,
                              spmv_flops)
from acg_tpu.solvers.stats import (SolverStats, StoppingCriteria,
                                   cg_flops_per_iteration)


def _spmv_fn(kernels):
    """Select the SpMV implementation: "xla" = ops.spmv (compiler-fused);
    "pallas"/"pallas-interpret" = the hand-written single-x-pass DIA kernel
    (ops.pallas_kernels.dia_spmv, measured ~1.2x faster on TPU v5e --
    BASELINE.md); "xla-roll" = the cyclic-shift DIA formulation whose
    shifts XLA's SPMD partitioner turns into boundary collective-permutes
    (the sharded/multi-chip route, ops.spmv.dia_mv_roll).  Falls back to
    XLA for non-DIA / rectangular matrices.

    A CALLABLE ``kernels`` is used directly as ``f(A, x) -> y`` -- the
    hook for mesh-aware SpMV objects (parallel.sharded_dia.
    PallasRollSpmv); instances hash by identity, so each rides its own
    jit cache entry."""
    if callable(kernels):
        return kernels
    if kernels == "xla-roll":
        from acg_tpu.ops.spmv import dia_mv_roll

        def f(A, x):
            if isinstance(A, DiaMatrix) and A.ncols_padded == A.nrows:
                return dia_mv_roll(A.data, A.offsets, x)
            return spmv(A, x)

        return f
    if kernels.startswith("pallas"):
        from acg_tpu.ops.pallas_kernels import dia_spmv, stencil_spmv

        interp = kernels.endswith("interpret")

        def f(A, x):
            if isinstance(A, DiaMatrix) and A.ncols_padded == A.nrows:
                return dia_spmv(A.data, A.offsets, x, interpret=interp)
            if getattr(A, "kind", None) == "poisson" \
                    and hasattr(A, "matfree_apply"):
                # the matrix-free stencil's Pallas path: coefficient
                # masks generated IN-KERNEL while x streams through
                # VMEM once (falls back to the operator's XLA apply
                # off the single-window route)
                return stencil_spmv(A, x, interpret=interp)
            return spmv(A, x)

        return f
    return spmv


def _scalar_setup(dtype, precise: bool):
    """``(dot, sdt)``: the CG-scalar dot product and the scalar dtype for
    ``dtype`` vector storage.

    bf16 storage (the half-traffic tier; the designed deviation from the
    reference's all-f64 arithmetic, ``comm.h:180-183``) computes every
    scalar in f32: plain mode accumulates the dots in f32
    (``preferred_element_type``), precise mode runs the compensated dot2
    over f32-widened reads.  Either way only bf16 bytes cross HBM; the
    widening rides the VPU.  f32/f64 storage keeps its native scalar
    path (dot2 when ``precise``)."""
    sdt = acc_dtype(dtype)
    if jnp.dtype(dtype) == jnp.bfloat16:
        if precise:
            def dot(a, b):
                return dot2(a.astype(sdt), b.astype(sdt))
        else:
            def dot(a, b):
                return jnp.dot(a, b, preferred_element_type=sdt)
        return dot, sdt
    return (dot2 if precise else jnp.dot), sdt


@functools.partial(jax.tree_util.register_dataclass,
                   data_fields=["x", "niterations", "rnrm2", "r0nrm2",
                                "bnrm2", "x0nrm2", "dxnrm2", "converged",
                                "breakdown"],
                   meta_fields=[])
@dataclasses.dataclass
class CGResult:
    """Device-resident solve result (one host transfer at the end).

    ``breakdown`` is the detector flag (``detect=True`` programs): the
    loop exited because the residual went non-finite or (p, Ap)
    non-positive -- the host recovery policy (solvers.resilience)
    decides restart-vs-abort.  Always False when detection is off."""

    x: jax.Array
    niterations: jax.Array
    rnrm2: jax.Array
    r0nrm2: jax.Array
    bnrm2: jax.Array
    x0nrm2: jax.Array
    dxnrm2: jax.Array
    converged: jax.Array
    breakdown: jax.Array


def _tolerances(crit: StoppingCriteria, r0nrm2, x0nrm2, dtype):
    """Device-side residual/diff thresholds; 0 disables (cf. cg.c:844-848)."""
    res_tol = jnp.maximum(jnp.asarray(crit.residual_atol, dtype),
                          jnp.asarray(crit.residual_rtol, dtype) * r0nrm2)
    diff_tol = jnp.maximum(jnp.asarray(crit.diff_atol, dtype),
                           jnp.asarray(crit.diff_rtol, dtype) * x0nrm2)
    return res_tol, diff_tol


def _converged(rnrm2sqr, dxnrm2sqr, res_tol, diff_tol):
    ok = jnp.asarray(False)
    ok = ok | jnp.where(res_tol > 0, rnrm2sqr < res_tol * res_tol, False)
    ok = ok | jnp.where(diff_tol > 0, dxnrm2sqr < diff_tol * diff_tol, False)
    return ok


def _breakdown_guard(gamma, denom):
    """``(bad, alpha)``: the ONE breakdown predicate every detecting
    loop shares -- non-finite gamma/denominator, or a non-positive
    denominator while progress remains -- and the guarded step size
    (a jnp.where select, NOT a zeroed multiply: 0 * inf is NaN, so a
    multiplied-out alpha would still poison the frozen vectors)."""
    bad = ((~jnp.isfinite(denom)) | (~jnp.isfinite(gamma))
           | ((denom <= 0) & (gamma > 0)))
    return bad, jnp.where(bad, jnp.zeros_like(gamma), gamma / denom)


def _iterate(iter_body, init_state, gamma_of, maxits, res_tol,
             diff_tol, dx_of, unbounded: bool, init_gamma=None,
             bad_of=None):
    """Run the CG iteration to maxits (traced scalar) or convergence.

    ``iter_body(k, state)`` receives the 0-based iteration index -- the
    hook the deterministic fault injector (acg_tpu.faults) keys on.

    Loop-structure choice, measured on TPU v5e (poisson2d n=2048, f32):
      * `fori_loop` with a *traced* bound and a minimal carry runs at the
        same speed as a static bound (~0.43 ms/iter) -- so a dynamic
        maxits costs nothing and one compile serves every iteration cap;
      * a `while_loop` whose predicate reads a data-dependent scalar costs
        ~+0.2 ms/iter (the carry dependence defeats loop pipelining), and
        testing only every K-th iteration in an outer while is *worse*
        (~3.7 ms per chunk boundary drain).
    Hence: tolerance-free solves (benchmark mode) take the pure fori path
    with no convergence predicate at all -- the analog of the reference
    always running with a deferred, one-iteration-stale test
    (``cgcuda.c:980-1052``) -- and tolerance-driven solves pay for the
    per-iteration device-side test exactly like the reference's
    device-initiated variant (``cg-kernels-cuda.cu:948-957``).

    ``bad_of`` (breakdown detection, ``detect=True`` programs) reads the
    carried breakdown flag; a flagged state exits the loop early so the
    host recovery policy can act.  Detection forces the while path even
    for unbounded solves -- the ~+0.2 ms/iter predicate cost is the
    price of early exit, paid only when recovery is requested.
    """
    if unbounded and bad_of is None:
        state = jax.lax.fori_loop(0, maxits, iter_body, init_state)
        return maxits, state, jnp.asarray(True)

    def body(carry):
        k, state, _ = carry
        state = iter_body(k, state)
        done = (jnp.asarray(False) if unbounded else
                _converged(gamma_of(state), dx_of(state), res_tol, diff_tol))
        return (k + 1, state, done)

    def cond(carry):
        go = (~carry[2]) & (carry[0] < maxits)
        if bad_of is not None:
            go = go & (~bad_of(carry[1]))
        return go

    # init_gamma overrides the carried value for the entry test: the
    # pipelined recurrence carries gamma_prev = inf at entry, but an
    # already-converged start (r0 = 0) must return x0 in 0 iterations,
    # not divide 0/0 in the first update.
    init_done = (jnp.asarray(False) if unbounded else _converged(
        gamma_of(init_state) if init_gamma is None else init_gamma,
        dx_of(init_state), res_tol, diff_tol))
    k, state, done = jax.lax.while_loop(cond, body,
                                        (jnp.int32(0), init_state,
                                         init_done))
    if unbounded:
        # unbounded semantics: "converged" = ran the full budget without
        # a detected breakdown (the only early exit on this path)
        done = ~bad_of(state)
    return k, state, done


@functools.partial(jax.jit,
                   static_argnames=("unbounded", "needs_diff", "precise",
                                    "kernels", "detect", "fault", "trace",
                                    "progress", "precond", "health",
                                    "state_io"))
def _cg_program(A: DeviceMatrix, b, x0, res_atol, res_rtol, diff_atol,
                diff_rtol, maxits, unbounded: bool, needs_diff: bool,
                precise: bool = False, kernels: str = "xla",
                detect: bool = False, fault=None, trace: int = 0,
                progress: int = 0, precond=None, mstate=None,
                health=None, state_io: bool = False, carry=None,
                k_offset=None):
    """Whole classic-CG solve as one XLA program.

    ``precise`` switches the CG scalars' dot products to the compensated
    dot2 (acg_tpu.ops.precision): ~2x working precision for gamma and
    (p, t), which is what lets plain-f32 storage converge past the
    ~1e-6 relative-residual stall.  bf16 storage keeps every scalar in
    f32 (``_scalar_setup``) and rounds the updated vectors once on
    store, so only half-width bytes cross HBM.

    ``detect`` (the resilience tier) carries a breakdown flag: a
    non-finite gamma or non-positive (p, Ap) FREEZES the iterate --
    alpha/beta would otherwise launder the poison into x -- and exits
    the loop so the host recovery policy can restart from the last good
    x.  ``fault`` is a static acg_tpu.faults.FaultSpec the injector
    threads into the loop (None compiles the unchanged program).

    ``trace`` (the telemetry tier, acg_tpu.telemetry) rides a
    ``(trace, 4)`` ring buffer of per-iteration ``(||r||^2, alpha,
    beta, pAp)`` in the loop carry -- recorded device-side, fetched
    ONCE with the result, no per-iteration host traffic -- and makes
    the program return ``(CGResult, buffer)``.  ``progress`` emits a
    host heartbeat every that-many iterations (jax.debug.callback).
    Both are static: 0 compiles the byte-identical pristine program.

    ``precond`` (a static :class:`acg_tpu.precond.PrecondSpec`) turns
    the loop into PRECONDITIONED CG: ``mstate`` (the preconditioner
    state pytree, an ordinary argument) feeds ``z = M^-1 r`` after each
    residual update, the CG scalar becomes ``gamma = (r, z)``, and the
    carry grows one extra true-residual scalar ``rr = (r, r)`` so the
    convergence test (and the reported rnrm2) keep the UNpreconditioned
    meaning while the telemetry ring records the preconditioned norm.
    ``None`` compiles the byte-identical unpreconditioned program
    (pinned in tests/test_hlo_structure.py).

    ``health`` (a static :class:`acg_tpu.health.HealthSpec`) arms the
    numerical-health tier: every ``health.every`` iterations a
    ``lax.cond``-guarded audit recomputes the TRUE residual
    ``b - A x`` through this program's own SpMV and carries the
    relative gap ``||r_true - r_rec||/||b||`` in a 4-scalar audit
    vector (returned with the result; an extra ``gap`` ring column
    when telemetry is also armed); the stagnation/sign detectors and a
    tripped gap feed the breakdown flag (``detect`` must then be
    armed).  ``None`` compiles the byte-identical unaudited program.
    ``health.abft`` additionally arms the Huang-Abraham
    checksum-protected SpMV: the column checksum ``c = A^T 1`` is
    computed once at setup through this program's own SpMV and a
    ``lax.cond``-guarded in-loop test compares ``sum(A p)`` against
    ``(c, p)`` at the audit cadence -- silent bit-level corruption
    (``sdc:flip``) detected on device and routed into the breakdown
    path.

    ``state_io``/``carry`` (the survivability tier, acg_tpu.
    checkpoint): ``state_io`` makes the program ALSO return the final
    loop carry ``(r, p, gamma[, rr])`` (x rides the result already),
    and a non-None ``carry`` of that shape re-enters the recurrence
    exactly where a previous chunk left it (``x0`` then holds the
    snapshot iterate; the setup ``r = b - A x0`` is skipped, so the
    chunked trajectory is ITERATION-IDENTICAL to an uninterrupted
    run).  ``k_offset`` (chunked dispatches only; None otherwise) is
    the trajectory iteration this chunk starts at, so the health
    tier's audit/ABFT cadence stays phased to GLOBAL iteration
    numbers across chunk boundaries.  Disarmed programs never name
    any of the three and lower byte-identical code (pinned in
    tests/test_checkpoint.py)."""
    dtype = b.dtype
    dot, sdt = _scalar_setup(dtype, precise)
    store = (lambda v: v.astype(dtype)) if sdt != dtype else (lambda v: v)
    spmv_ = _spmv_fn(kernels)
    bnrm2 = jnp.sqrt(dot(b, b))
    x0nrm2 = jnp.sqrt(dot(x0, x0))
    if precond is not None:
        from acg_tpu.precond import make_apply
        papply = make_apply(precond, spmv_)
    if carry is not None:
        # resume: the provided carry IS the loop state; nothing is
        # recomputed, so the Krylov recurrence continues exactly
        if precond is not None:
            r, p, gamma, rr = carry
            r0nrm2 = jnp.sqrt(rr)
        else:
            r, p, gamma = carry
            r0nrm2 = jnp.sqrt(gamma)
    elif precond is not None:
        r = b - spmv_(A, x0)
        z0 = papply(mstate, A, r)
        p = store(z0)
        gamma = dot(r, z0)
        rr = dot(r, r)
        r0nrm2 = jnp.sqrt(rr)
    else:
        r = b - spmv_(A, x0)
        p = r
        gamma = dot(r, r)
        r0nrm2 = jnp.sqrt(gamma)
    res_tol = jnp.maximum(res_atol, res_rtol * r0nrm2)
    diff_tol = jnp.maximum(diff_atol, diff_rtol * x0nrm2)
    inf = jnp.asarray(jnp.inf, sdt)

    if trace or progress:
        from acg_tpu import telemetry
    if health is not None:
        from acg_tpu import health as _health
    if health is not None and health.abft:
        # the column checksum c = A^T 1 (= A 1: symmetric systems),
        # through THIS program's own SpMV selection -- one extra SpMV
        # per solve, zero per-check SpMVs
        cvec = spmv_(A, jnp.ones_like(b)).astype(sdt)

        def dot3(a1, c1, a2, c2, a3, c3):
            return dot(a1, c1), dot(a2, c2), dot(a3, c3)

    # carry layout: (x, r, p, gamma [, rr] [, dx] [, bad] [, aud]
    # [, ring]) -- rr (the true residual the convergence test reads)
    # joins only under precond, dx only under a diff criterion, the
    # audit vector only under an armed health spec
    dx_i = 5 if precond is not None else 4

    # dxsqr joins the carry only when a diff criterion is active: every
    # extra loop-carried scalar measurably slows the TPU loop (~0.1 ms/it)
    def body(k, state):
        if trace:
            buf, state = state[-1], state[:-1]
        if health is not None:
            aud, state = state[-1], state[:-1]
        x, r, p, gamma = state[:4]
        # NOT the fused dia_spmv_dot: measured in-loop, the in-kernel
        # (p,t) scalar costs ~15% (1,355 vs 1,589 iters/s interleaved
        # A/B) -- the opaque kernel boundary forfeits XLA's fusion of
        # the updates, the same verdict as the fused 6-vector update
        # (BASELINE.md)
        t = spmv_(A, p)
        if fault is not None:
            t = fault.apply_spmv(t, k)
        pdott = dot(p, t)
        if fault is not None:
            pdott = fault.apply_dot(pdott, k)
        if detect:
            # breakdown BEFORE the updates: a non-finite t/pdott or an
            # indefiniteness signal must not reach x
            bad, alpha = _breakdown_guard(gamma, pdott)
            x = store(jnp.where(bad, x, x + alpha * p))
            r = store(jnp.where(bad, r, r - alpha * t))
        else:
            alpha = gamma / pdott
            x = store(x + alpha * p)
            r = store(r - alpha * t)
        if precond is not None:
            z = papply(mstate, A, r)
            if fault is not None:
                z = fault.apply_precond(z, k)
            gamma_next = dot(r, z)
            rr_next = dot(r, r)
            beta = gamma_next / gamma
            p_next = store(z + beta * p)
            out = (x, r, p_next, gamma_next, rr_next)
        else:
            gamma_next = dot(r, r)
            beta = gamma_next / gamma
            p_next = store(r + beta * p)
            out = (x, r, p_next, gamma_next)
        if needs_diff:
            dx = alpha * alpha * dot(p, p)
            if detect:
                # freeze dx too: a zeroed alpha would make the frozen
                # iteration "satisfy" the diff criterion and launder the
                # breakdown into a converged exit
                dx = jnp.where(bad, state[dx_i], dx)
            out = out + (dx,)
        fire = None
        if health is not None:
            # the health cadence is phased to TRAJECTORY iterations:
            # chunked dispatches (the checkpoint tier) pass the chunk's
            # starting iteration so audits fire at the same global
            # iterations as an uninterrupted run
            kk = k if k_offset is None else k + k_offset

            # in-loop true-residual audit: b - A x through THIS
            # program's SpMV, guarded by lax.cond so non-audited
            # iterations pay only the predicate
            def compute_gap():
                return _health.relative_gap(b - spmv_(A, x), r,
                                            dot, bnrm2, sdt)

            aud, fire = _health.audit_update(aud, health, kk, compute_gap)
            # residual non-decrease, measured on the scalar the
            # convergence test reads (preconditioned: the carried rr)
            prog_now = out[4] if precond is not None else gamma_next
            prog_prev = state[4] if precond is not None else gamma
            aud = _health.stall_update(aud, health,
                                       prog_now < prog_prev)
            if health.abft:
                # Huang-Abraham checksum test of this iteration's
                # t = A p against the precomputed column checksum
                aud = _health.abft_update(aud, health, kk, t, p, cvec,
                                          dot3, sdt, t.shape[0])
        if detect:
            # a poison that slipped past pdott (e.g. a NaN row of t with
            # a finite dot) lands in r: flag it one iteration deferred.
            # Under precond, a NEGATIVE (r, z) is the non-SPD-M signal
            # (the precond: fault site's deterministic twin)
            deferred = bad | (~jnp.isfinite(gamma_next))
            if precond is not None:
                deferred = deferred | (gamma_next < 0)
            if health is not None:
                if precond is None:
                    # sign anomaly: a negative computed (r, r) is
                    # arithmetic poison the finite-value guard misses
                    deferred = deferred | (gamma_next < 0)
                deferred = deferred | _health.trip(aud, health)
            out = out + (deferred,)
        if health is not None:
            out = out + (aud,)
        if trace:
            # record the RAW scalars (a poisoned pdott/gamma_next stays
            # visible in the window the recovery log quotes); under
            # precond gamma IS the preconditioned residual norm^2
            audit_col = (_health.ring_gap(aud, fire, sdt)
                         if health is not None else None)
            out = out + (telemetry.ring_record(buf, k, gamma_next, alpha,
                                               beta, pdott,
                                               audit=audit_col),)
        if progress:
            telemetry.heartbeat(k, gamma_next, progress)
        return out

    # the audit vector and ring buffer ride LAST in the carry (in that
    # order) so every existing index (dx, the deferred-bad freeze
    # reads) is untouched; only the tail accessors below shift
    init_state = (x0, r, p, gamma)
    if precond is not None:
        init_state = init_state + (rr,)
    init_state = init_state + ((inf,) if needs_diff else ())
    if detect:
        init_state = init_state + (jnp.asarray(False),)
    if health is not None:
        init_state = init_state + (_health.audit_init(sdt, health),)
    if trace:
        init_state = init_state + (telemetry.ring_init(
            trace, sdt, audit=health is not None),)
    ntail = (1 if trace else 0) + (1 if health is not None else 0)
    bad_i = -1 - ntail
    # the convergence test reads the TRUE residual either way: gamma
    # itself unpreconditioned, the carried rr under precond
    conv_i = 4 if precond is not None else 3
    k, state, done = _iterate(
        body, init_state, lambda s: s[conv_i], maxits,
        res_tol, diff_tol,
        (lambda s: s[dx_i]) if needs_diff else (lambda s: inf),
        unbounded, bad_of=(lambda s: s[bad_i]) if detect else None)
    x, r, p, gamma = state[:4]
    rnrm2sqr = state[4] if precond is not None else gamma
    dxsqr = state[dx_i] if needs_diff else inf
    breakdown = state[bad_i] if detect else jnp.asarray(False)
    # a breakdown flagged on the same iteration the tolerance was met is
    # convergence, not breakdown: at the f32 floor the (p, Ap) scalar
    # legitimately rounds to <= 0 once progress is exhausted
    breakdown = breakdown & ~done
    res = CGResult(x=x, niterations=k, rnrm2=jnp.sqrt(rnrm2sqr),
                   r0nrm2=r0nrm2, bnrm2=bnrm2, x0nrm2=x0nrm2,
                   dxnrm2=jnp.sqrt(dxsqr), converged=done,
                   breakdown=breakdown)
    extras = ()
    if trace:
        extras = extras + (state[-1],)
    if health is not None:
        extras = extras + (state[-2] if trace else state[-1],)
    if state_io:
        # the loop carry, strictly last (x rides the result already):
        # what the checkpoint chunk driver snapshots and threads into
        # the next dispatch's ``carry``
        core = (r, p, gamma)
        if precond is not None:
            core = core + (state[4],)
        extras = extras + (core,)
    return (res,) + extras if extras else res


@functools.partial(jax.jit,
                   static_argnames=("K", "unbounded", "restart", "kernels"))
def _cg_replaced_program(A: DeviceMatrix, b, x0, res_atol, res_rtol,
                         maxits, K: int, unbounded: bool, restart: bool,
                         kernels: str = "xla"):
    """Classic CG over bf16 vector storage made SOUND by periodic f32
    residual replacement -- the accuracy contract for the half-traffic
    tier at high condition numbers.

    Plain bf16 CG diverges once kappa exceeds ~1/u_bf16 ~ 500 (measured:
    rel residual 1e3 after 1000 iterations at the flagship's kappa ~
    1.7e6, BASELINE.md): the bf16-rounded r/p recurrences drift off the
    true residual and the drift compounds.  The classical fix (residual
    replacement, Van der Vorst & Ye) bounds the drift window: every
    ``K`` iterations the true residual ``r = b - A x`` is recomputed in
    f32 and swapped in for the recurrence residual.

    Device layout: ``x`` accumulates in f32 but is NOT touched by the
    inner loop -- each segment accumulates its correction ``d`` from
    zero in bf16 and adds it to x once per segment, so the per-iteration
    HBM traffic stays identical to the plain bf16 tier (~40 B/row on the
    5-point flagship) and the replacement costs one mixed-precision
    SpMV (bf16 planes x f32 vector, f32 accumulation -- lossless for
    bf16-exact stencil values, the ``--dtype mixed`` arithmetic) per K
    iterations: ~2% at K=50.  ``d``'s bf16 rounding does not feed back
    into the inner recurrences at all (r evolves independently); it only
    caps the per-segment residual reduction, and the next replacement
    measures whatever reduction was actually achieved, so the outer
    iteration is self-correcting -- iterative refinement with an
    inner-bf16-CG solver (the same structure as solvers.refine, fully
    device-resident).

    ``restart=True`` additionally resets ``p = r`` at each replacement
    (restarted CG: discards Krylov memory, maximally stable);
    ``restart=False`` carries ``p`` across segments (classical residual
    replacement: keeps the convergence rate, slightly less protection).
    Convergence is tested once per segment on the TRUE f32 residual --
    so unlike the plain tiers, a converged report from this program is
    grounded in an f32-accurate residual by construction.

    The role of the reference's strictly-f64 contract (``comm.h:
    180-183``) restated for TPU storage tiers; SURVEY.md section 7
    "hard parts" (f64-on-TPU mitigation ladder).
    """
    sdt = jnp.float32
    vdt = jnp.bfloat16
    spmv_ = _spmv_fn(kernels)

    def dot(u, v):
        return jnp.dot(u, v, preferred_element_type=sdt)

    b = b.astype(sdt)
    x0 = x0.astype(sdt)
    bnrm2 = jnp.sqrt(dot(b, b))
    x0nrm2 = jnp.sqrt(dot(x0, x0))
    r32 = b - spmv_(A, x0)
    gamma32 = dot(r32, r32)
    r0nrm2 = jnp.sqrt(gamma32)
    res_tol = jnp.maximum(res_atol.astype(sdt), res_rtol.astype(sdt) * r0nrm2)
    inf = jnp.asarray(jnp.inf, sdt)

    def segment(x32, r32, p, its):
        """One replacement period: inner bf16 CG on A d = r32 from d=0,
        then x += d and ONE f32-accurate SpMV for the fresh residual.

        The inner loop's trip count is the STATIC K even when fewer
        iterations remain (a carry-dependent bound would compile to a
        dynamic-trip loop XLA cannot software-pipeline -- measured 0.55x
        the plain-bf16 rate, uniformly over K and mode); the final
        partial segment instead masks the updates of its dead tail via
        ``live`` (at most K-1 wasted iterations per solve, and none at
        all when maxits is a multiple of K, as in the bench protocol)."""
        r = r32.astype(vdt)
        gamma = dot(r, r)
        if restart:
            p = r
        else:
            # carried-direction health check: at kappa*u_bf16 >> 1 the
            # bf16 p-recurrence can blow up across segments (beta > 1
            # sustained); once p overflows, alpha*p = 0*inf would poison
            # d with NaNs.  Reset the direction to r when it has grown
            # out of all proportion to the residual -- a restart at
            # exactly the boundaries where one is needed.
            pn = dot(p, p)
            bad = (~jnp.isfinite(pn)) | (pn > jnp.asarray(1e24, sdt) * gamma)
            p = jnp.where(bad, r, p)
        nin = jnp.minimum(jnp.int32(K), maxits - its)

        def ibody(j, st):
            d, r, p, gamma = st
            live = j < nin
            t = spmv_(A, p)
            pdott = dot(p, t)
            # carried directions (restart=False) are not orthogonal to
            # the replaced residual, so the classic numerator gamma =
            # (r, r) misestimates the step along p -- measured:
            # catastrophic overshoot (rel residual 1e18).  The general
            # line-search numerator (r, p) reduces to gamma under exact
            # conjugacy and stays correct without it; restarted segments
            # keep the cheaper classic form.
            num = gamma if restart else dot(r, p)
            # breakdown guards: bf16 rounding noise can drive (p, Ap)
            # to 0 or negative once the segment's progress is
            # exhausted; freeze the updates (alpha = 0) instead of
            # poisoning d -- the next replacement resets the segment
            # either way.  The same freeze implements the dead tail of
            # the final partial segment.
            alpha = jnp.where(live & (pdott > 0), num / pdott,
                              jnp.zeros_like(gamma))
            d = (d.astype(sdt) + alpha * p.astype(sdt)).astype(vdt)
            r_new = (r.astype(sdt) - alpha * t.astype(sdt)).astype(vdt)
            gamma_next = dot(r_new, r_new)
            beta = jnp.where(gamma > 0, gamma_next / gamma,
                             jnp.zeros_like(gamma))
            # alpha = 0 already freezes d and r; p needs an explicit
            # select (beta freezes at 1 there, which would add r to p)
            p = jnp.where(live,
                          (r_new.astype(sdt)
                           + beta * p.astype(sdt)).astype(vdt), p)
            return (d, r_new, p, gamma_next)

        d, _, p, _ = jax.lax.fori_loop(
            0, K, ibody, (jnp.zeros_like(r), r, p, gamma))
        x32 = x32 + d.astype(sdt)
        r32 = b - spmv_(A, x32)
        return x32, r32, p, its + nin, dot(r32, r32)

    p0 = r32.astype(vdt)
    if unbounded:
        nouter = (maxits + jnp.int32(K) - 1) // jnp.int32(K)

        def obody(_, carry):
            x32, r32, p, its, _ = carry
            return segment(x32, r32, p, its)

        x32, r32f, _, its, gamma_f = jax.lax.fori_loop(
            0, nouter, obody, (x0, r32, p0, jnp.int32(0), gamma32))
        # per-segment true-residual breakdown flag: the replacement
        # machinery IS this tier's detector (a poisoned segment leaves a
        # non-finite recomputed residual), no in-loop cost
        return CGResult(x=x32, niterations=its, rnrm2=jnp.sqrt(gamma_f),
                        r0nrm2=r0nrm2, bnrm2=bnrm2, x0nrm2=x0nrm2,
                        dxnrm2=inf, converged=jnp.isfinite(gamma_f),
                        breakdown=~jnp.isfinite(gamma_f))

    def wcond(carry):
        _, _, _, its, gamma = carry
        return (gamma >= res_tol * res_tol) & (its < maxits)

    def wbody(carry):
        x32, r32, p, its, _ = carry
        return segment(x32, r32, p, its)

    x32, r32f, _, its, gamma_f = jax.lax.while_loop(
        wcond, wbody, (x0, r32, p0, jnp.int32(0), gamma32))
    # a non-finite recomputed residual exits wcond (NaN >= x is False):
    # the segment boundary doubles as the breakdown detector for free
    return CGResult(x=x32, niterations=its, rnrm2=jnp.sqrt(gamma_f),
                    r0nrm2=r0nrm2, bnrm2=bnrm2, x0nrm2=x0nrm2,
                    dxnrm2=inf, converged=gamma_f < res_tol * res_tol,
                    breakdown=~jnp.isfinite(gamma_f))


@functools.partial(jax.jit,
                   static_argnames=("unbounded", "interpret"))
def _cg_fused_program(A: DeviceMatrix, b, x0, res_atol, res_rtol,
                      maxits, unbounded: bool, interpret: bool = False):
    """Whole classic-CG solve with the TWO-PHASE fused iteration
    (ops.pallas_kernels.cg_phase_a/b): the reference's monolithic
    device-kernel concept (``cg-kernels-cuda.cu:627-970``) done the TPU
    way -- each iteration is exactly two streamed kernels with scalars
    in SMEM, ~15 HBM passes vs the XLA formulation's ~20 (and ~12.5
    with bf16 planes).  Unlike round 2's single fused kernels, nothing
    is left outside the kernels for XLA to fuse, so there is no fusion
    to forfeit.  Scalars are f32 throughout; supports residual criteria
    (the carried gamma IS the fresh ||r||^2 -- the convergence test is
    free) but not diff criteria."""
    from acg_tpu.ops.pallas_kernels import cg_phase_a, cg_phase_b

    dtype = b.dtype
    sdt = jnp.float32
    bnrm2 = jnp.sqrt(jnp.dot(b, b, preferred_element_type=sdt))
    x0nrm2 = jnp.sqrt(jnp.dot(x0, x0, preferred_element_type=sdt))
    r = b - spmv(A, x0)
    gamma = jnp.dot(r, r, preferred_element_type=sdt)
    r0nrm2 = jnp.sqrt(gamma)
    res_tol = jnp.maximum(res_atol.astype(sdt),
                          res_rtol.astype(sdt) * r0nrm2)
    inf = jnp.asarray(jnp.inf, sdt)
    p0 = jnp.zeros_like(b)

    def body(st):
        x, r, p, gamma, gamma_prev = st
        p, t, pdott = cg_phase_a(A.data, A.offsets, r, p, gamma,
                                 gamma_prev, interpret=interpret)
        x, r, gamma_next = cg_phase_b(x, p, r, t, gamma, pdott,
                                      interpret=interpret)
        return (x, r, p, gamma_next, gamma)

    init = (x0, r, p0, gamma, inf)
    if unbounded:
        state = jax.lax.fori_loop(0, maxits, lambda _, s: body(s), init)
        k, done = maxits, jnp.asarray(True)
    else:
        def wcond(carry):
            k, st, done = carry
            return (~done) & (k < maxits)

        def wbody(carry):
            k, st, _ = carry
            st = body(st)
            return (k + 1, st, st[3] < res_tol * res_tol)

        k, state, done = jax.lax.while_loop(
            wcond, wbody, (jnp.int32(0), init, gamma < res_tol * res_tol))
    x, r_fin, _, gamma_fin, _ = state
    return CGResult(x=x, niterations=k, rnrm2=jnp.sqrt(gamma_fin),
                    r0nrm2=r0nrm2, bnrm2=bnrm2, x0nrm2=x0nrm2,
                    dxnrm2=inf, converged=done,
                    breakdown=jnp.asarray(False))


@functools.partial(jax.jit,
                   static_argnames=("unbounded", "needs_diff", "precise",
                                    "kernels", "detect", "fault", "trace",
                                    "progress", "precond", "health",
                                    "state_io"))
def _cg_pipelined_program(A: DeviceMatrix, b, x0, res_atol, res_rtol,
                          diff_atol, diff_rtol, maxits, unbounded: bool,
                          needs_diff: bool, precise: bool = False,
                          kernels: str = "xla", detect: bool = False,
                          fault=None, trace: int = 0, progress: int = 0,
                          precond=None, mstate=None, health=None,
                          state_io: bool = False, carry=None,
                          k_offset=None):
    """Whole pipelined-CG (Ghysels-Vanroose) solve as one XLA program.

    ``detect``/``fault``/``trace``/``progress`` as in
    :func:`_cg_program`.  The pipelined recurrences are the brittle ones
    (deep pipelining amplifies rounding -- Cornelis & Vanroose,
    arXiv:1801.04728), and a poisoned q/w shows up one iteration
    deferred in the (w, r) reduction: detection here is inherently one
    iteration stale, like the convergence test.  The telemetry window
    records the CARRIED gamma = ||r||^2 from before the update (the
    same one-iteration-stale quantity the convergence test uses) and
    the alpha denominator in the pAp slot -- exactly the recurrence
    scalars whose drift the deep-pipelining literature plots.

    ``precond``/``mstate`` arm the PRECONDITIONED pipelined variant
    (Ghysels-Vanroose's M^-1 formulation, the method arXiv:1801.04728 /
    1905.06850 actually pipeline): the carry grows ``u = M^-1 r`` and
    ``q = M^-1 s`` plus the extra ``w/m/n`` recurrences -- one
    preconditioner apply (``m = M^-1 w``) and one SpMV (``n = A m``)
    per iteration, both overlapping the fused reduction exactly like
    the unpreconditioned q = A w.  The fused reduction carries THREE
    scalars (gamma = (r, u), delta = (w, u), rr = (r, r)) so the mesh
    tiers keep the single-allreduce property.  ``None`` compiles the
    byte-identical unpreconditioned program.

    ``health`` (acg_tpu.health.HealthSpec) arms the in-loop
    true-residual audit + stagnation/sign detectors exactly as in
    :func:`_cg_program` -- this is the tier the audit matters MOST for:
    the pipelined recurrences are the ones whose recursively-updated
    residual drifts from ``b - A x`` (arXiv:1801.04728), and the audit
    measures that drift with the loop's own SpMV."""
    dtype = b.dtype
    dot, sdt = _scalar_setup(dtype, precise)
    store = (lambda v: v.astype(dtype)) if sdt != dtype else (lambda v: v)
    spmv_ = _spmv_fn(kernels)
    bnrm2 = jnp.sqrt(dot(b, b))
    x0nrm2 = jnp.sqrt(dot(x0, x0))
    if precond is not None:
        from acg_tpu.precond import make_apply
        papply = make_apply(precond, spmv_)
    # resume (the survivability tier): a provided carry re-enters the
    # GV recurrence exactly -- x0 holds the snapshot iterate, and the
    # carried vectors (incl. w = A-image and the z/t/q scratch whose
    # recurrences the pipelined variant never rebuilds) replace the
    # whole setup.  carry layout matches checkpoint.carry_names
    c_in = None
    if carry is not None:
        c_in = carry
        if precond is not None:
            r, rr0 = c_in[0], c_in[9]
            r0nrm2 = jnp.sqrt(rr0)
        else:
            r = c_in[0]
            r0nrm2 = jnp.sqrt(jnp.maximum(c_in[5], 0))
    elif precond is not None:
        r = b - spmv_(A, x0)
        u0 = store(papply(mstate, A, r))
        w = spmv_(A, u0)
        rr0 = dot(r, r)
        r0nrm2 = jnp.sqrt(rr0)
    else:
        r = b - spmv_(A, x0)
        w = spmv_(A, r)
        r0nrm2 = jnp.sqrt(dot(r, r))
    res_tol = jnp.maximum(res_atol, res_rtol * r0nrm2)
    diff_tol = jnp.maximum(diff_atol, diff_rtol * x0nrm2)
    inf = jnp.asarray(jnp.inf, sdt)
    zeros = jnp.zeros_like(b)
    if trace or progress:
        from acg_tpu import telemetry
    if health is not None:
        from acg_tpu import health as _health
    if health is not None and health.abft:
        # column checksum through this program's own SpMV (see
        # _cg_program); the pipelined test verifies q = A w / n = A m
        cvec = spmv_(A, jnp.ones_like(b)).astype(sdt)

        def dot3(a1, c1, a2, c2, a3, c3):
            return dot(a1, c1), dot(a2, c2), dot(a3, c3)

    def pbody(k, state):
        """Preconditioned GV body: carry (x, r, u, w, p, s, q, z,
        gamma_prev, alpha_prev, rr) -- s is the A-direction (the
        unpreconditioned t), z the A M^-1 A-direction, q the M^-1
        A-direction."""
        if trace:
            buf, state = state[-1], state[:-1]
        if health is not None:
            aud, state = state[-1], state[:-1]
        x, r, u, w, p, s, q, z, gamma_prev, alpha_prev = state[:10]
        rr_prev = state[10]
        # the iteration's three reductions, fused (ONE allreduce on a
        # mesh): gamma/delta drive the recurrences, rr feeds the true-
        # residual convergence test (stale by one, like gamma)
        gamma = dot(r, u)
        delta = dot(w, u)
        rr = dot(r, r)
        if fault is not None:
            delta = fault.apply_dot(delta, k)
        # m = M^-1 w and n = A m overlap the reduction under XLA's
        # scheduler -- the preconditioned restatement of q = A w
        m = papply(mstate, A, w)
        if fault is not None:
            m = fault.apply_precond(m, k)
        nvec = spmv_(A, m)
        if fault is not None:
            nvec = fault.apply_spmv(nvec, k)
        beta = gamma / gamma_prev               # inf -> 0 on first iteration
        denom = delta - beta * (gamma / alpha_prev)
        if detect:
            bad, alpha = _breakdown_guard(gamma, denom)
            # a negative (r, u) is the non-SPD-M signal (precond: fault
            # twin); the unpreconditioned guard cannot see it
            bad = bad | (gamma < 0)
            alpha = jnp.where(bad, jnp.zeros_like(alpha), alpha)
        else:
            alpha = gamma / denom
        z = store(nvec + beta * z)
        q = store(m + beta * q)
        s = store(w + beta * s)
        p = store(u + beta * p)
        if detect:
            x = store(jnp.where(bad, x, x + alpha * p))
            r = store(jnp.where(bad, r, r - alpha * s))
            u = store(jnp.where(bad, u, u - alpha * q))
            w = store(jnp.where(bad, w, w - alpha * z))
        else:
            x = store(x + alpha * p)
            r = store(r - alpha * s)
            u = store(u - alpha * q)
            w = store(w - alpha * z)
        out = (x, r, u, w, p, s, q, z, gamma, alpha, rr)
        if needs_diff:
            dx = alpha * alpha * dot(p, p)
            if detect:
                dx = jnp.where(bad, state[11], dx)
            out = out + (dx,)
        fire = None
        if health is not None:
            kk = k if k_offset is None else k + k_offset

            def compute_gap():
                return _health.relative_gap(b - spmv_(A, x), r,
                                            dot, bnrm2, sdt)

            aud, fire = _health.audit_update(aud, health, kk, compute_gap)
            # progress measured on the fused (r, r) scalar (stale by
            # one, like the convergence test)
            aud = _health.stall_update(aud, health, rr < rr_prev)
            if health.abft:
                # checksum test of this iteration's n = A m
                aud = _health.abft_update(aud, health, kk, nvec, m,
                                          cvec, dot3, sdt,
                                          nvec.shape[0])
        if detect:
            flag = bad
            if health is not None:
                flag = flag | _health.trip(aud, health)
            out = out + (flag,)
        if health is not None:
            out = out + (aud,)
        if trace:
            # gamma = the PRECONDITIONED residual norm^2 (stale by one,
            # like the convergence test); alpha denominator in pAp slot
            audit_col = (_health.ring_gap(aud, fire, sdt)
                         if health is not None else None)
            out = out + (telemetry.ring_record(buf, k, gamma, alpha,
                                               beta, denom,
                                               audit=audit_col),)
        if progress:
            telemetry.heartbeat(k, gamma, progress)
        return out

    def body(k, state):
        if trace:
            buf, state = state[-1], state[:-1]
        if health is not None:
            aud, state = state[-1], state[:-1]
        x, r, w, p, t, z, gamma_prev, alpha_prev = state[:8]
        # both reductions of the iteration, fused (one allreduce on a mesh)
        gamma = dot(r, r)
        delta = dot(w, r)
        if fault is not None:
            delta = fault.apply_dot(delta, k)
        # SpMV overlaps the allreduce in the reference (cgcuda.c:1750-1790);
        # under XLA the scheduler owns that overlap.
        q = spmv_(A, w)
        if fault is not None:
            q = fault.apply_spmv(q, k)
        # the SpMV input, before the 6-vector update rebinds w below
        # (the ABFT check verifies q against THIS vector)
        w_in = w
        beta = gamma / gamma_prev               # inf -> 0 on first iteration
        denom = delta - beta * (gamma / alpha_prev)
        if detect:
            # the alpha denominator plays the (p, Ap) role here; freeze
            # x/r/w on breakdown (p/t/z are scratch once the loop exits)
            bad, alpha = _breakdown_guard(gamma, denom)
            if health is not None:
                # sign anomaly: a negative computed (r, r) is
                # arithmetic poison (the finite-value guard misses it)
                bad = bad | (gamma < 0)
                alpha = jnp.where(bad, jnp.zeros_like(alpha), alpha)
        else:
            alpha = gamma / denom
        # the 6-vector update stays in XLA even under kernels="pallas":
        # the hand-written fused kernel (ops.pallas_kernels.
        # fused_pipelined_update) wins in isolation (~1.35x) but inside
        # the loop it is an opaque call that forfeits XLA's fusion of the
        # *next* iteration's dots into these writes -- measured 894 vs
        # 1818 iters/s on the flagship (BASELINE.md)
        z = store(q + beta * z)
        t = store(w + beta * t)
        p = store(r + beta * p)
        if detect:
            x = store(jnp.where(bad, x, x + alpha * p))
            r = store(jnp.where(bad, r, r - alpha * t))
            w = store(jnp.where(bad, w, w - alpha * z))
        else:
            x = store(x + alpha * p)
            r = store(r - alpha * t)
            w = store(w - alpha * z)
        out = (x, r, w, p, t, z, gamma, alpha)
        if needs_diff:
            dx = alpha * alpha * dot(p, p)
            if detect:
                # freeze dx on breakdown (see _cg_program): alpha = 0
                # must not fake the diff criterion
                dx = jnp.where(bad, state[8], dx)
            out = out + (dx,)
        fire = None
        if health is not None:
            kk = k if k_offset is None else k + k_offset

            def compute_gap():
                return _health.relative_gap(b - spmv_(A, x), r,
                                            dot, bnrm2, sdt)

            aud, fire = _health.audit_update(aud, health, kk, compute_gap)
            aud = _health.stall_update(aud, health, gamma < gamma_prev)
            if health.abft:
                # checksum test of this iteration's q = A w (w_in: the
                # pre-update input that produced q)
                aud = _health.abft_update(aud, health, kk, q, w_in,
                                          cvec, dot3, sdt, q.shape[0])
        if detect:
            flag = bad
            if health is not None:
                flag = flag | _health.trip(aud, health)
            out = out + (flag,)
        if health is not None:
            out = out + (aud,)
        if trace:
            # the carried gamma (stale by one, like the convergence
            # test) and the alpha denominator in the pAp slot
            audit_col = (_health.ring_gap(aud, fire, sdt)
                         if health is not None else None)
            out = out + (telemetry.ring_record(buf, k, gamma, alpha,
                                               beta, denom,
                                               audit=audit_col),)
        if progress:
            telemetry.heartbeat(k, gamma, progress)
        return out

    # convergence tests the carried gamma = ||r||^2 from *before* the
    # update -- one iteration stale, the reference's deferred test
    # (cgcuda.c:1798-1810); saves a fresh dot per iteration.  The
    # preconditioned carry tests the carried rr (same staleness), so
    # tolerances keep the true-residual meaning
    if precond is not None:
        if c_in is not None:
            init_state = (x0,) + tuple(c_in) + (
                (inf,) if needs_diff else ())
        else:
            init_state = (x0, r, u0, w, zeros, zeros, zeros, zeros, inf,
                          inf, rr0) + ((inf,) if needs_diff else ())
        loop_body = pbody
        conv_of = lambda s: s[10]
        dx_of = (lambda s: s[11]) if needs_diff else (lambda s: inf)
        init_gamma = rr0
    else:
        if c_in is not None:
            init_state = (x0,) + tuple(c_in) + (
                (inf,) if needs_diff else ())
            init_gamma = c_in[5]
        else:
            init_state = (x0, r, w, zeros, zeros, zeros, inf, inf) + (
                (inf,) if needs_diff else ())
            init_gamma = r0nrm2 * r0nrm2
        loop_body = body
        conv_of = lambda s: s[6]
        dx_of = (lambda s: s[8]) if needs_diff else (lambda s: inf)
    if detect:
        init_state = init_state + (jnp.asarray(False),)
    if health is not None:
        init_state = init_state + (_health.audit_init(sdt, health),)
    if trace:
        init_state = init_state + (telemetry.ring_init(
            trace, sdt, audit=health is not None),)
    ntail = (1 if trace else 0) + (1 if health is not None else 0)
    bad_i = -1 - ntail
    k, state, done = _iterate(
        loop_body, init_state, conv_of, maxits,
        res_tol, diff_tol, dx_of,
        unbounded, init_gamma=init_gamma,
        bad_of=(lambda s: s[bad_i]) if detect else None)
    x, r = state[0], state[1]
    dxsqr = ((state[11] if precond is not None else state[8])
             if needs_diff else inf)
    breakdown = state[bad_i] if detect else jnp.asarray(False)
    rnrm2 = jnp.sqrt(dot(r, r))
    # the in-loop test is one iteration stale; at the maxits boundary a
    # solve whose final *fresh* residual meets tolerance must not report
    # converged=False with a below-tolerance rnrm2 in the same stats block
    done = jnp.logical_or(done, rnrm2 <= res_tol)
    # ... and a breakdown whose frozen residual already meets tolerance
    # is convergence: near the floor the pipelined denominator
    # legitimately rounds <= 0 (the recurrences' known brittleness)
    breakdown = breakdown & ~done
    res = CGResult(x=x, niterations=k, rnrm2=rnrm2, r0nrm2=r0nrm2,
                   bnrm2=bnrm2, x0nrm2=x0nrm2, dxnrm2=jnp.sqrt(dxsqr),
                   converged=done, breakdown=breakdown)
    extras = ()
    if trace:
        extras = extras + (state[-1],)
    if health is not None:
        extras = extras + (state[-2] if trace else state[-1],)
    if state_io:
        # the GV loop carry, strictly last (checkpoint.carry_names
        # order minus x, which rides the result)
        core = tuple(state[1:11] if precond is not None
                     else state[1:8])
        extras = extras + (core,)
    return (res,) + extras if extras else res


class JaxCGSolver:
    """Single-device CG solver over a :class:`DeviceMatrix`.

    The role of ``acgsolvercuda_init/solvempi/solve_pipelined`` with
    commsize==1 (``cgcuda.c:143-332,403-1917``): keeps the matrix and
    workspace device-resident across solves and accumulates statistics.
    """

    def __init__(self, A: DeviceMatrix, pipelined: bool = False,
                 precise_dots: bool = False, kernels: str = "auto",
                 vector_dtype=None, replace_every: int = 0,
                 replace_restart: bool = True, recovery=None,
                 host_matrix=None, trace: int = 0, progress: int = 0,
                 precond=None, health=None, ckpt=None, algorithm=None):
        """``recovery`` (a :class:`acg_tpu.solvers.resilience.
        RecoveryPolicy`) arms breakdown detection in the compiled loop
        plus the host-side restart policy; ``host_matrix`` (scipy CSR)
        additionally enables the final host-solver fallback rung.
        Detection also arms automatically while the fault injector
        (acg_tpu.faults) is active, so injected faults are never
        silently laundered into a returned x.

        ``trace`` (iterations; 0 = off) arms the in-loop convergence
        telemetry ring (acg_tpu.telemetry): the last solve's trailing
        window lands on ``self.last_trace`` / ``stats.trace`` with one
        extra device fetch per solve.  ``progress`` (iterations; 0 =
        off) emits an in-loop heartbeat to stderr.  Both reach the
        direct classic/pipelined programs only -- the replacement and
        fused tiers refuse at solve time rather than silently record
        nothing (the fault-injector rationale).

        ``vector_dtype`` decouples vector storage from matrix storage
        (default: the matrix dtype).  The supported mix is bf16 matrix +
        f32 vectors (``--dtype mixed``): for matrices whose entries are
        exactly representable in bf16 (Poisson stencils: -1, 4, 6) the
        arithmetic is IDENTICAL to all-f32 -- the f32-accumulating SpMV
        reads the planes losslessly -- while matrix HBM traffic halves.
        Unlike the all-bf16 tier it has no kappa limit: bf16 vector
        storage caps convergence at kappa ~ 1/u_bf16 ~ 500 (measured:
        diverges on 2D Poisson n >= 512), whereas this tier's iterates
        never touch bf16.

        ``precond`` (an :class:`acg_tpu.precond.PrecondSpec`, a spec
        string like ``"jacobi"``/``"bjacobi:32"``/``"cheby:4"``, or
        None) arms preconditioned CG / pipelined CG: the state is built
        once (lazily, on device) and rides the solve programs as an
        argument; ``None`` leaves every lowered program byte-identical
        to an unpreconditioned build.

        ``health`` (an :class:`acg_tpu.health.HealthSpec` or None) arms
        the numerical-health tier: the in-loop true-residual audit
        (every ``health.every`` iterations, through this tier's own
        SpMV), the stagnation/sign detectors, and -- for tripping
        actions -- the breakdown path + recovery hand-off.  ``None``
        leaves every lowered program byte-identical to an unaudited
        build (pinned in tests/test_hlo_structure.py)."""
        self.A = A
        self.vector_dtype = vector_dtype
        self.pipelined = pipelined
        self.precise_dots = precise_dots
        # recurrence selection (acg_tpu.recurrence): classic/pipelined
        # resolve onto the existing hand-built programs (byte-identical
        # dispatch -- the builder emission is pinned equal in
        # tests/test_hlo_structure.py); sstep:S / pipelined:L dispatch
        # the communication-avoiding builder programs
        from acg_tpu.recurrence import parse_algorithm
        self.algo = parse_algorithm(algorithm)
        if self.algo is not None and not self.algo.communication_avoiding:
            self.pipelined = pipelined = (self.algo.kind == "pipelined")
            self.algo = None
        self._lam = None  # cached (lmin, lmax) spectral estimate
        if kernels == "auto":
            # the Pallas kernels win on TPU hardware (BASELINE.md); off
            # TPU they would run interpreted (slow), the measured win
            # only exists for the f32/bf16 fast path, and under x64 mode
            # Mosaic lowers index maps as i64 (rejected by TPU memrefs)
            # -- so auto gates on all three and falls back to XLA
            itemsize = (np.dtype(A.dtype).itemsize
                        if isinstance(A, DiaMatrix) else 0)
            kernels = ("pallas" if jax.default_backend() == "tpu"
                       and itemsize in (2, 4)
                       and not jax.config.jax_enable_x64 else "xla")
        elif kernels == "pallas" and jax.default_backend() != "tpu":
            kernels = "pallas-interpret"
        elif kernels == "pallas" and jax.config.jax_enable_x64:
            # Mosaic lowers x64-mode BlockSpec index maps as i64, which
            # the TPU memref ops reject: compiled Pallas needs x64 off
            raise ValueError("kernels='pallas' cannot compile with "
                             "jax_enable_x64 on TPU; disable x64 or use "
                             "kernels='xla'")
        elif kernels in ("fused", "fused-interpret"):
            from acg_tpu.ops.pallas_kernels import fused_cg_route

            if pipelined:
                raise ValueError("kernels='fused' implements classic CG "
                                 "on the single-device tier (use the "
                                 "pipelined variant with kernels="
                                 "'pallas'/'xla'; the DIST mesh fused "
                                 "tier supports pipelined)")
            if precise_dots:
                raise ValueError("kernels='fused' accumulates its dots "
                                 "in plain f32 SMEM; compensated dots "
                                 "(precise_dots) need kernels='xla'/"
                                 "'pallas'")
            vdt = (jnp.dtype(vector_dtype) if vector_dtype is not None
                   else matrix_dtype(A))
            if not (isinstance(A, DiaMatrix)
                    and A.ncols_padded == A.nrows
                    and fused_cg_route(A.offsets, A.nrows, vdt) is not None):
                raise ValueError("kernels='fused' needs a square DIA "
                                 "matrix on the single-window kernel "
                                 "route")
            if jax.default_backend() != "tpu":
                kernels = "fused-interpret"
            elif kernels == "fused" and jax.config.jax_enable_x64:
                # Mosaic lowers x64-mode index maps as i64, which the
                # TPU memref ops reject; compiled Pallas needs x64 off
                # (explicit 'fused-interpret' never compiles -> exempt)
                raise ValueError("kernels='fused' cannot compile with "
                                 "jax_enable_x64 on TPU; disable x64 "
                                 "or use kernels='xla'")
        if kernels not in ("xla", "xla-roll", "pallas", "pallas-interpret",
                           "fused", "fused-interpret"):
            raise ValueError(f"unknown kernels choice {kernels!r}")
        self.replace_every = int(replace_every)
        self.replace_restart = bool(replace_restart)
        if self.replace_every < 0:
            raise ValueError("replace_every must be >= 0 (a negative "
                             "period would compile a non-terminating "
                             "segment loop)")
        if self.replace_every:
            vdt = (jnp.dtype(vector_dtype) if vector_dtype is not None
                   else jnp.dtype(matrix_dtype(A)))
            if vdt != jnp.bfloat16:
                raise ValueError(
                    "replace_every is the bf16 tier's accuracy contract "
                    "(periodic f32 residual replacement); f32/f64 vector "
                    "storage has no replacement drift to correct -- use "
                    "precise_dots or a RefinedSolver there")
            if pipelined:
                raise ValueError("replace_every implements classic CG "
                                 "(the pipelined recurrence carries w=Ar, "
                                 "which replacement would invalidate)")
            if precise_dots:
                raise ValueError("replace_every computes its scalars in "
                                 "plain f32 (the bf16 tier's scalar "
                                 "path); precise_dots needs the direct "
                                 "programs")
            if kernels.startswith("fused"):
                raise ValueError("replace_every composes with "
                                 "kernels='xla'/'pallas' (the fused "
                                 "two-phase iteration has no replacement "
                                 "hook)")
        # matrix-free operator tier (acg_tpu.ops.operator): the apply
        # rides every program through the ops.spmv dispatch, so no
        # per-program changes exist -- but bf16 vector storage has no
        # matrix traffic to halve here (the planes are generated) and
        # its kappa cap buys nothing: refuse rather than run a
        # pointless degraded tier
        self._matfree = hasattr(A, "matfree_apply")
        if self._matfree:
            vdt = (jnp.dtype(vector_dtype) if vector_dtype is not None
                   else jnp.dtype(matrix_dtype(A)))
            if vdt == jnp.bfloat16:
                raise ValueError(
                    "matrix-free operators generate their plane values "
                    "in the storage dtype and have no matrix HBM "
                    "traffic for bf16 to halve; use f32/f64 vectors "
                    "(the assembled tiers keep the bf16 contract)")
        from acg_tpu.precond import parse_precond
        self.precond_spec = parse_precond(precond)
        if self.precond_spec is not None:
            if self.replace_every:
                raise ValueError(
                    "precond does not compose with replace_every: the "
                    "replacement segments restructure the recurrences "
                    "the preconditioner threads through (use the direct "
                    "classic/pipelined PCG programs)")
            if isinstance(kernels, str) and kernels.startswith("fused"):
                raise ValueError(
                    "kernels='fused' folds the whole iteration into two "
                    "streamed kernels and has no preconditioner hook; "
                    "precond needs kernels='xla'/'pallas'")
        # the preconditioner state pytree (device arrays); built lazily
        # at first dispatch so construction stays zero-transfer
        self._mstate = None
        # numerical-health tier (acg_tpu.health): the audit/detector
        # spec rides the direct programs as a static argument; the
        # replacement/fused tiers have no audit hook (the replacement
        # segments ARE periodic true-residual recomputation, and the
        # fused kernels fold the whole iteration), so an armed spec
        # refuses there rather than silently audit nothing
        if health is not None:
            from acg_tpu.health import HealthSpec
            if not isinstance(health, HealthSpec):
                raise ValueError("health must be an "
                                 "acg_tpu.health.HealthSpec or None")
            if not health.armed:
                health = None
        if health is not None:
            if self.replace_every:
                raise ValueError(
                    "the true-residual audit (health) does not compose "
                    "with replace_every: the replacement segments "
                    "already recompute b - A x every K iterations -- "
                    "the audit would measure its own mechanism")
            if isinstance(kernels, str) and kernels.startswith("fused"):
                raise ValueError(
                    "kernels='fused' folds the whole iteration into "
                    "two streamed kernels and has no audit hook; the "
                    "health tier needs kernels='xla'/'pallas'")
        self.health_spec = health
        # survivability tier (acg_tpu.checkpoint): an armed
        # CheckpointConfig turns solve() into the host-chunked
        # snapshot driver.  The chunking threads the FULL loop carry
        # through the direct programs, which the replacement/fused
        # tiers cannot expose -- refuse rather than silently skip
        # snapshots (the fault-injector discipline)
        if ckpt is not None:
            from acg_tpu.checkpoint import CheckpointConfig
            if not isinstance(ckpt, CheckpointConfig):
                raise ValueError("ckpt must be an acg_tpu.checkpoint."
                                 "CheckpointConfig or None")
            if replace_every:
                raise ValueError(
                    "checkpointing (ckpt) does not compose with "
                    "replace_every: the replacement segments' inner "
                    "state never leaves the program (use the direct "
                    "classic/pipelined programs)")
            if isinstance(kernels, str) and kernels.startswith("fused"):
                raise ValueError(
                    "kernels='fused' folds the whole iteration into "
                    "two streamed kernels and exposes no loop carry; "
                    "checkpointing needs kernels='xla'/'pallas'")
        self.ckpt = ckpt
        if self.algo is not None:
            # the communication-avoiding recurrences run unpreconditioned
            # over f32/f64 vectors and compose with telemetry, faults,
            # recovery and (sstep) the health audit; everything they do
            # NOT reach refuses here rather than silently dropping (the
            # could-never-fire discipline)
            ca = str(self.algo)
            if pipelined:
                raise ValueError(
                    f"--algorithm {ca} selects its own recurrence; it "
                    f"does not compose with the pipelined flag (use "
                    f"--algorithm pipelined for Ghysels-Vanroose)")
            if self.replace_every:
                raise ValueError(
                    f"{ca} does not compose with replace_every (the "
                    f"replacement segments restructure the recurrence)")
            if self.precise_dots:
                raise ValueError(
                    f"{ca} accumulates its fused Gram/window reductions "
                    f"in the scalar dtype; precise_dots composes with "
                    f"the classic/pipelined programs")
            if self.precond_spec is not None:
                raise ValueError(
                    f"{ca} runs unpreconditioned: the s-step basis and "
                    f"the p(l) auxiliary basis have no M^-1 hook yet "
                    f"(use --algorithm classic|pipelined with --precond)")
            if isinstance(kernels, str) and kernels.startswith("fused"):
                raise ValueError(
                    f"{ca} needs kernels='xla'/'pallas' (the fused "
                    f"two-phase iteration folds the classic recurrence)")
            vdt = (jnp.dtype(vector_dtype) if vector_dtype is not None
                   else jnp.dtype(matrix_dtype(A)))
            if vdt == jnp.bfloat16:
                raise ValueError(
                    f"{ca} amplifies storage rounding through its basis "
                    f"products; bf16 vectors need the classic/pipelined "
                    f"tiers (replace_every is the bf16 contract)")
            if ckpt is not None and ckpt.repartition:
                raise ValueError(
                    f"{ca} snapshots its own carry layout "
                    f"(checkpoint.ca_carry_names) which is not in the "
                    f"field-compatible repartition set; "
                    f"--resume-repartition needs --algorithm "
                    f"classic|pipelined")
            if (ckpt is not None and self.algo.kind == "pl"
                    and int(trace) > 0):
                raise ValueError(
                    f"{ca} checkpoints its pipeline counters in the "
                    f"ABSOLUTE iteration frame, but the trace ring is "
                    f"reconstructed chunk-relative; --ckpt with --trace "
                    f"needs --algorithm classic|pipelined|sstep")
            if self.health_spec is not None:
                if self.algo.kind == "pl":
                    raise ValueError(
                        f"{ca} has no in-loop audit hook (the basis "
                        f"recovery already detects its own breakdown); "
                        f"--audit-every needs classic/pipelined/sstep")
                if self.health_spec.abft:
                    raise ValueError(
                        f"{ca} has no checksum hook for its basis "
                        f"products; --abft needs classic/pipelined")
        self.kernels = kernels
        self.recovery = recovery
        if (self.algo is not None and self.algo.kind == "pl"
                and recovery is None):
            # restarted p(l)-CG: the square-root breakdown of the deep
            # pipeline is an EXPECTED algorithmic event; arm the
            # standard restart ladder with the algorithm's own budget
            # (recurrence.pl_restart_policy) so a breakdown restarts
            # from the current iterate instead of raising
            from acg_tpu.recurrence import pl_restart_policy
            self.recovery = pl_restart_policy()
        self.host_matrix = host_matrix
        self.trace = int(trace)
        self.progress = int(progress)
        if self.trace < 0 or self.progress < 0:
            raise ValueError("trace/progress must be >= 0 (iteration "
                             "counts; 0 disables)")
        # the last solve's ConvergenceTrace (telemetry tier), also on
        # stats.trace; None until a traced solve ran
        self.last_trace = None
        self.stats = SolverStats(unknowns=A.nrows)
        # the matrix the solve PROGRAMS consume; defaults to A.  The
        # sharded pallas-roll tier swaps in a per-shard-padded twin
        # whose planes suit the windowed kernel while self.A stays the
        # clean view every other consumer (manufactured, refine, spot
        # check) expects (parallel.sharded_dia.use_pallas_roll)
        self._A_program: DeviceMatrix = A
        # lazy: the device nnz count (for the flop statistic) runs at
        # first stats use, not construction -- a solver over on-device
        # planes must construct with zero transfers (VERDICT round 2)
        self._spmv_flops_cache: float | None = None

    @property
    def _spmv_flops(self) -> float:
        if self._spmv_flops_cache is None:
            self._spmv_flops_cache = spmv_flops(self.A)
        return self._spmv_flops_cache

    def _solve_dtype(self):
        """The vector dtype a solve converts b/x0 to: the matrix dtype
        unless ``vector_dtype`` overrides it; the replacement tier's
        outer iteration owns b/x0 in f32 (rounding b to bf16 would bake
        a u_bf16-sized backward error into every replaced residual)."""
        dtype = matrix_dtype(self.A)
        if self.vector_dtype is not None:
            dtype = jnp.dtype(self.vector_dtype)
        if self.replace_every:
            dtype = jnp.dtype(jnp.float32)
        return dtype

    def _ensure_precond_state(self):
        """Build (once, lazily) the preconditioner state pytree that
        rides the solve programs as an argument: diagonal / block
        factors extracted from the CLEAN matrix view ``self.A``, the
        Chebyshev lambda_max power iteration run through the SAME SpMV
        selection the programs dispatch (``self._A_program`` -- the
        per-shard-padded twin on the pallas-roll tier)."""
        if self.precond_spec is None or self._mstate is not None:
            return self._mstate
        from acg_tpu.precond import setup_single
        sdt = acc_dtype(self._solve_dtype())
        self._mstate = setup_single(self.precond_spec, self.A,
                                    _spmv_fn(self.kernels), sdt,
                                    A_program=self._A_program)
        return self._mstate

    def _ensure_lam(self):
        """Cached (lmin, lmax) spectral interval for the
        communication-avoiding recurrences (Chebyshev s-step basis,
        p(l) shifts): one power iteration through THIS tier's own SpMV
        selection at first dispatch; (0, 0) when the armed recurrence
        never reads it (monomial basis)."""
        if self._lam is None:
            from acg_tpu.recurrence import estimate_lam
            if self.algo is not None and self.algo.needs_lam:
                self._lam = estimate_lam(
                    self._A_program, self.A.nrows,
                    acc_dtype(self._solve_dtype()), kernels=self.kernels)
            else:
                self._lam = (0.0, 0.0)
        return self._lam

    def _select_program(self, b, x0, crit: StoppingCriteria,
                        detect: bool = False, fault=None):
        """``(program, args, kwargs, traced)``: this configuration's
        whole-solve program dispatch -- ONE function shared by
        :meth:`solve` and :meth:`lower_solve`, so the observability tier
        (:mod:`acg_tpu.perfmodel`) interrogates EXACTLY the program a
        solve runs, never a reconstruction that could drift.  ``b``/``x0``
        must already be device arrays in :meth:`_solve_dtype`.  Raises
        the same configuration refusals a solve would."""
        # tolerances ride in the scalar dtype (f32 for bf16 storage) so a
        # 1e-9 rtol is not pre-rounded to 8 mantissa bits
        sdt = acc_dtype(b.dtype)
        telem = self.trace or self.progress
        if self.algo is not None:
            # communication-avoiding recurrences (acg_tpu.recurrence):
            # the builder programs composed with this tier's SpMV
            # selection.  ``lam`` rides between the tolerances and
            # maxits so the recovery ladder's generic restart arg
            # surgery (args[5:-1]) carries it through restarts
            from acg_tpu import recurrence as rec
            if crit.needs_diff:
                raise ValueError(
                    f"{self.algo} supports residual criteria only (the "
                    f"coefficient-space/pipelined updates carry no "
                    f"||dx|| scalar)")
            lam = self._ensure_lam()
            if self.algo.kind == "sstep":
                program = rec._cg_sstep_program
                args = (self._A_program, b, x0,
                        jnp.asarray(crit.residual_atol, sdt),
                        jnp.asarray(crit.residual_rtol, sdt),
                        (jnp.asarray(lam[0], sdt),
                         jnp.asarray(lam[1], sdt)),
                        jnp.int32(crit.maxits))
                kwargs = dict(s=self.algo.param, basis=self.algo.basis,
                              unbounded=crit.unbounded,
                              kernels=self.kernels, fault=fault,
                              trace=self.trace, progress=self.progress)
                if self.health_spec is not None:
                    kwargs["health"] = self.health_spec
            else:
                program = rec._cg_pl_program
                args = (self._A_program, b, x0,
                        jnp.asarray(crit.residual_atol, sdt),
                        jnp.asarray(crit.residual_rtol, sdt),
                        (jnp.asarray(lam[0], sdt),
                         jnp.asarray(lam[1], sdt)),
                        jnp.int32(crit.maxits))
                kwargs = dict(l=self.algo.param,
                              unbounded=crit.unbounded,
                              kernels=self.kernels, fault=fault,
                              trace=self.trace, progress=self.progress)
            return program, args, kwargs, bool(self.trace)
        if self.replace_every:
            if crit.needs_diff:
                raise ValueError("replace_every supports residual "
                                 "criteria only (the diff criterion has "
                                 "no meaning across replacement segments)")
            if telem:
                # the replacement program's inner fori does not thread
                # a global iteration index, so the telemetry hooks
                # would silently record nothing -- refuse (the fault-
                # injector rationale)
                raise AcgError(
                    ErrorCode.INVALID_VALUE,
                    "convergence telemetry (trace/progress) does not "
                    "reach the replacement-segment program "
                    "(replace_every); use the direct classic/pipelined "
                    "programs")
            if fault is not None:
                # the replacement program's inner fori does not thread a
                # global iteration index, so an armed injector would
                # silently never fire -- refuse rather than report a
                # clean solve the operator believes was fault-tested
                raise AcgError(
                    ErrorCode.INVALID_VALUE,
                    "fault injection does not reach the replacement-"
                    "segment program (replace_every); inject into the "
                    "direct classic/pipelined programs instead")
            program = _cg_replaced_program
            args = (self._A_program, b, x0,
                    jnp.asarray(crit.residual_atol, sdt),
                    jnp.asarray(crit.residual_rtol, sdt),
                    jnp.int32(crit.maxits))
            kwargs = dict(K=self.replace_every, unbounded=crit.unbounded,
                          restart=self.replace_restart,
                          kernels=self.kernels)
        elif (isinstance(self.kernels, str)
              and self.kernels.startswith("fused")):
            if crit.needs_diff:
                raise ValueError("kernels='fused' supports residual "
                                 "criteria only")
            if detect:
                raise AcgError(
                    ErrorCode.INVALID_VALUE,
                    "kernels='fused' folds its scalars into "
                                 "the two streamed kernels and has no "
                                 "breakdown-detection hook; recovery/"
                                 "fault injection need kernels='xla'/"
                                 "'pallas'")
            if telem:
                raise AcgError(
                    ErrorCode.INVALID_VALUE,
                    "kernels='fused' keeps its scalars in SMEM inside "
                    "the two streamed kernels; convergence telemetry "
                    "(trace/progress) needs kernels='xla'/'pallas'")
            program = _cg_fused_program
            args = (self._A_program, b, x0,
                    jnp.asarray(crit.residual_atol, sdt),
                    jnp.asarray(crit.residual_rtol, sdt),
                    jnp.int32(crit.maxits))
            kwargs = dict(unbounded=crit.unbounded,
                          interpret=self.kernels.endswith("interpret"))
        else:
            program = _cg_pipelined_program if self.pipelined else _cg_program
            args = (self._A_program, b, x0,
                    jnp.asarray(crit.residual_atol, sdt),
                    jnp.asarray(crit.residual_rtol, sdt),
                    jnp.asarray(crit.diff_atol, sdt),
                    jnp.asarray(crit.diff_rtol, sdt),
                    jnp.int32(crit.maxits))
            kwargs = dict(unbounded=crit.unbounded,
                          needs_diff=crit.needs_diff,
                          precise=self.precise_dots, kernels=self.kernels,
                          detect=detect, fault=fault,
                          trace=self.trace, progress=self.progress)
            if self.precond_spec is not None:
                # the disarmed call site stays byte-identical: neither
                # kwarg is passed at all without a spec
                kwargs["precond"] = self.precond_spec
                kwargs["mstate"] = self._ensure_precond_state()
            if self.health_spec is not None:
                # same discipline: an unaudited build never even names
                # the kwarg
                kwargs["health"] = self.health_spec
        tr = self.trace and not (self.replace_every
                                 or (isinstance(self.kernels, str)
                                     and self.kernels.startswith("fused")))
        return program, args, kwargs, tr

    def lower_solve(self, b, x0=None, criteria=None):
        """Lower (but do not run) the EXACT whole-solve XLA program this
        configuration dispatches for ``(b, x0, criteria)`` and return
        the ``jax.stages.Lowered`` handle -- the observability hook the
        perfmodel tier (:mod:`acg_tpu.perfmodel`) compiles to extract
        the compiler's own cost/memory analysis.

        Never mutates solver state, and shares :meth:`_select_program`
        with :meth:`solve`, so the lowered program is byte-identical to
        the one a solve compiles (asserted in tests/test_hlo_structure.
        py).  Breakdown detection mirrors a clean solve: armed iff a
        recovery policy is set.  The fault injector is deliberately NOT
        consulted -- analysis describes the pristine program."""
        crit = criteria or StoppingCriteria()
        dtype = self._solve_dtype()
        b = jnp.asarray(b, dtype=dtype)
        x0 = (jnp.zeros_like(b) if x0 is None
              else jnp.asarray(x0, dtype=dtype))
        program, args, kwargs, _ = self._select_program(
            b, x0, crit, detect=self._detect(None), fault=None)
        return program.lower(*args, **kwargs)

    def _detect(self, fault) -> bool:
        """Whether the compiled loop carries the breakdown flag:
        recovery armed, an active injector, or a health spec whose
        detectors trip the breakdown path -- shared by solve() and the
        lower_solve hook so the analyzed program is the dispatched
        one."""
        return (self.recovery is not None or fault is not None
                or (self.health_spec is not None
                    and self.health_spec.arms_detect))

    def _fault_refusals(self, fault) -> None:
        """Armed-injector configurations this tier can never fire:
        refuse instead of reporting a clean 'fault-tested' solve --
        shared by the plain and checkpoint-chunked solve paths."""
        from acg_tpu import faults
        spec = faults.active_fault()
        if (spec is not None and spec.site == "crash"
                and (self.ckpt is None or self.ckpt.path is None)):
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                "crash:exit fires from the checkpoint chunk driver "
                "between snapshots; arm --ckpt FILE --ckpt-every K "
                "(a crash with no snapshot to resume from proves "
                "nothing)")
        if fault is None:
            return
        if fault.site == "halo":
            # no halo exists on the single-device solver: an armed
            # injector that can never fire must refuse, not report a
            # clean "fault-tested" solve (the replace_every rationale)
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                "halo fault injection needs a distributed problem with "
                "ghost exchange (DistCGSolver, nparts > 1); the "
                "single-device solver has no halo to poison")
        if (fault is not None and fault.site == "precond"
                and self.precond_spec is None):
            # no preconditioner is armed: the apply the fault poisons
            # never runs (the replace_every rationale)
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                "precond fault injection needs an armed preconditioner "
                "(--precond jacobi|bjacobi|cheby:K); this solve runs "
                "unpreconditioned CG")
        if (self.algo is not None and fault is not None
                and self.algo.kind == "sstep"
                and fault.site in ("spmv", "sdc", "halo")
                and fault.iteration % self.algo.param != 0):
            # the s-step basis products carry the BLOCK-START iteration
            # index: a vector fault armed mid-block could never fire
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                f"sstep:{self.algo.param} applies SpMV/halo faults at "
                f"block boundaries; arm an iteration that is a "
                f"multiple of {self.algo.param} (got "
                f"{fault.iteration})")
        if (self.algo is not None and fault is not None
                and self.algo.kind == "pl" and fault.site == "dot"):
            # p(l) has no scalar dot in its loop (the window reduction
            # is a fused matvec): the armed injector could never fire
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                "dot fault injection has no site in the p(l) "
                "recurrence (its reductions are fused window matvecs); "
                "use spmv:, or the classic/pipelined/sstep programs")
        if fault is not None and fault.part > 0:
            # _fault_nparts distinguishes the true single-device solver
            # from multi-part subclasses that reuse this solve (the
            # sharded roll tier): NEITHER can honour part targeting --
            # these programs apply faults to the global vector -- but
            # the diagnosis must name the right reason
            if getattr(self, "_fault_nparts", 1) == 1:
                raise AcgError(
                    ErrorCode.INVALID_VALUE,
                    f"fault spec targets part {fault.part}, but the "
                    f"single-device solver has only part 0 -- the fault "
                    f"could never fire")
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                f"the sharded single-program tier applies faults to the "
                f"global vector and cannot target part {fault.part}; "
                f"drop part= or use the partitioned DistCGSolver path "
                f"for part-targeted injection")

    def solve(self, b, x0=None, criteria: StoppingCriteria | None = None,
              raise_on_divergence: bool = True, warmup: int = 0,
              host_result: bool = True) -> np.ndarray:
        """Solve Ax=b.  ``host_result=False`` returns the device array
        instead of copying x to the host -- at pod-filling sizes the
        copy dwarfs the solve (537 MB for 512^3), and a caller that only
        needs the timing/stats (benchmarks) or feeds x to another device
        computation should not pay it.  The FP-exception report then
        comes from a device-side finiteness check instead of the host
        scan.

        An armed checkpoint (``ckpt``) routes through the survivability
        tier's chunked driver (:meth:`_solve_ckpt`): same programs,
        dispatched in snapshot-bounded chunks."""
        if self.ckpt is not None:
            return self._solve_ckpt(b, x0=x0, criteria=criteria,
                                    raise_on_divergence=raise_on_divergence,
                                    warmup=warmup,
                                    host_result=host_result)
        crit = criteria or StoppingCriteria()
        st = self.stats
        st.criteria = crit
        from acg_tpu import faults
        fault = faults.device_fault()
        self._fault_refusals(fault)
        # detection arms with the recovery policy, an active injector
        # (an injected fault must surface, never launder into x), or a
        # tripping health spec; the detect=False programs stay
        # byte-identical to the seed's
        detect = self._detect(fault)
        # dtype policy (vector_dtype override, f32 replacement outer)
        # lives in _solve_dtype, shared with the lower_solve hook
        dtype = self._solve_dtype()
        from acg_tpu import telemetry
        if fault is not None:
            # timestamped twin of the injector's stderr line for the
            # structured sink (--stats-json)
            telemetry.record_event(st, "fault-armed",
                                   f"{fault.site}:{fault.mode}"
                                   f"@{fault.iteration}")
        t_xfer = time.perf_counter()
        with telemetry.annotate("transfer"):
            b = jnp.asarray(b, dtype=dtype)
            x0 = (jnp.zeros_like(b) if x0 is None
                  else jnp.asarray(x0, dtype=dtype))
        telemetry.add_timing(st, "transfer",
                             time.perf_counter() - t_xfer)
        # scalar dtype for recovery's re-derived tolerances below; the
        # program dispatch itself -- tolerances, static kwargs, the
        # configuration refusals -- is shared with lower_solve
        sdt = acc_dtype(dtype)
        program, args, kwargs, tr = self._select_program(
            b, x0, crit, detect=detect, fault=fault)

        hl = "health" in kwargs

        def run(*a, **kw):
            """One program invocation, normalised to
            (CGResult, ring, audit-vector)."""
            out = program(*a, **kw)
            if not tr and not hl:
                return out, None, None
            out = out if isinstance(out, tuple) else (out,)
            return (out[0], out[1] if tr else None,
                    out[-1] if hl else None)

        def attempt_trace(res, tbuf):
            """The ONE host fetch of a traced solve: un-rotate this
            attempt's ring against its iteration count."""
            if tbuf is None:
                return None
            return telemetry.ConvergenceTrace.from_ring(
                np.asarray(tbuf), int(res.niterations),
                solver=self._solver_name())

        # warmup solves outside the timed region (the reference warms up
        # each op class before timing, cgcuda.c:612-710).  device_sync,
        # not bare block_until_ready: the tunneled backend has been
        # observed to return from block instantly while the program
        # still runs, which would zero every tsolve (_platform).
        from acg_tpu._platform import block_until_ready_works, device_sync
        block_until_ready_works()  # resolve the cached probe OUTSIDE timing
        t_warm = time.perf_counter()
        with telemetry.annotate("compile"):
            for _ in range(max(warmup, 0)):
                device_sync(run(*args, **kwargs)[0].x)
        if warmup > 0:
            # warmup absorbs the compile; with warmup=0 it lands in the
            # solve phase (documented in the README observability notes)
            telemetry.add_timing(st, "compile",
                                 time.perf_counter() - t_warm)
        t0 = time.perf_counter()
        with telemetry.annotate("solve"):
            res, tbuf, aud = run(*args, **kwargs)
            device_sync(res.x)
        niter = int(res.niterations)
        first_norms = None
        # the first note_audit of this solve resets the health summary;
        # later attempts MERGE (gap_max keeps the worst gap that
        # tripped, naudits accumulates across restarts).  gap_tripped
        # remembers whether the LATEST attempt's exit was a gap trip,
        # so the no-rungs-left raise below can name the real cause
        # instead of the generic arithmetic-breakdown diagnosis
        aud_fresh = True
        gap_tripped = False
        if detect and bool(res.breakdown):
            # host-side recovery (solvers.resilience): bounded restarts
            # from the last finite iterate -- the program's setup
            # recomputes the TRUE residual r = b - A x0, so each restart
            # discards the poisoned recurrence state -- then the host-
            # solver fallback, then a diagnosis-carrying raise
            from acg_tpu.solvers.resilience import RecoveryDriver
            driver = RecoveryDriver(self.recovery, st, "jax-cg")
            x0_dev = args[2]
            # the stats block reports the ORIGINAL solve's norms; the
            # restarted attempts' r0/x0 are recovery internals
            first_norms = (float(res.bnrm2), float(res.x0nrm2),
                           float(res.r0nrm2))
            # restarts keep the FIRST attempt's residual target: the
            # rtol baseline is r0 of the original x0, not of the restart
            # (re-baselining would demand an unreachable 1e-6 reduction
            # of an already-small restart residual)
            abs_tol = max(crit.residual_atol,
                          crit.residual_rtol * float(res.r0nrm2))
            while bool(res.breakdown):
                k_done = int(res.niterations)
                if hl and aud is not None:
                    # this attempt's audit evidence BEFORE the restart
                    # decision: an accuracy_degraded event marks a gap
                    # trip apart from an arithmetic breakdown, and the
                    # restart's true-residual recompute IS the
                    # residual-replacement fix
                    from acg_tpu import health as health_mod
                    gap_tripped = health_mod.note_audit(
                        st, aud, self.health_spec, "jax-cg",
                        fresh=aud_fresh)
                    aud_fresh = False
                if tr:
                    # the trajectory that led INTO the breakdown -- the
                    # evidence the post-hoc stats block cannot show
                    st.trace = self.last_trace = attempt_trace(res, tbuf)
                    driver.log_trace_window(st.trace)
                if gap_tripped and self.health_spec.action == "abort":
                    # host-tier parity: --on-gap abort is a hard stop,
                    # the restart budget belongs to replace -- without
                    # this an armed recovery policy would silently turn
                    # abort into replace
                    st.tsolve += time.perf_counter() - t0
                    st.converged = False
                    from acg_tpu.errors import BreakdownError
                    raise BreakdownError(
                        f"jax-cg: true-residual gap "
                        f"{st.health.get('gap_max', 0.0):.3e} exceeds "
                        f"threshold {self.health_spec.threshold:g} at "
                        f"iteration {niter} (--on-gap abort)")
                if driver.on_breakdown(k_done):
                    x_next = res.x
                    if not bool(jnp.isfinite(x_next).all()):
                        driver.record("iterate non-finite; restarting "
                                      "from the initial guess")
                        x_next = x0_dev
                    if fault is not None and "fault" in kwargs:
                        if (self.algo is not None
                                and self.algo.kind == "sstep"
                                and fault.device_site
                                and fault.iteration <= k_done):
                            # the poisoned basis block froze BEFORE
                            # executing its steps, so niterations never
                            # passes the fault's index: the fault FIRED
                            # -- vanish it (the chunk drivers'
                            # vanish-not-rebase rationale) instead of
                            # rebasing it into the restart's first block
                            fault = None
                            kwargs["fault"] = None
                        elif (self.algo is not None
                              and self.algo.kind == "pl"):
                            # p(l) faults key on the AUXILIARY-basis
                            # counter j, which runs l ahead of the
                            # trajectory count (j = adv + l exactly at
                            # a breakdown, since advances only freeze
                            # on exit conditions): shift in the
                            # z-counter frame so a fired fault vanishes
                            # (shift -> None) instead of re-triggering
                            # the same breakdown across every restart
                            fault = fault.shift(
                                k_done + self.algo.param + 1)
                            kwargs["fault"] = fault
                        else:
                            fault = fault.shift(k_done)
                            kwargs["fault"] = fault
                    if self.precond_spec is not None:
                        # preserve finite preconditioner state across
                        # the restart, rebuild it when poisoned
                        from acg_tpu.precond import refresh_state
                        if refresh_state(self, driver):
                            kwargs["mstate"] = self._mstate
                    remaining = max(crit.maxits - niter, 1)
                    args = (args[:2] + (x_next,)
                            + (jnp.asarray(abs_tol, sdt),
                               jnp.asarray(0.0, sdt)) + args[5:-1]
                            + (jnp.int32(remaining),))
                    res, tbuf, aud = run(*args, **kwargs)
                    device_sync(res.x)
                    niter += int(res.niterations)
                    continue
                pol = self.recovery
                if (pol is not None and pol.fallback_host
                        and self.host_matrix is not None):
                    driver.on_fallback("fallback: host reference solver")
                    st.tsolve += time.perf_counter() - t0
                    return self._host_fallback(
                        b, crit, raise_on_divergence, host_result)
                st.tsolve += time.perf_counter() - t0
                st.converged = False
                if gap_tripped:
                    # name the REAL cause: this exit was an accuracy
                    # gate, not arithmetic poison (host-tier parity)
                    from acg_tpu.errors import BreakdownError
                    raise BreakdownError(
                        f"jax-cg: true-residual gap "
                        f"{st.health.get('gap_max', 0.0):.3e} exceeds "
                        f"threshold {self.health_spec.threshold:g} at "
                        f"iteration {niter} (--on-gap "
                        f"{self.health_spec.action}); "
                        f"{st.nrestarts} restart(s) exhausted and no "
                        f"fallback available")
                raise driver.give_up(niter, float(res.rnrm2))
        t_solve = time.perf_counter() - t0
        st.tsolve += t_solve
        telemetry.add_timing(st, "solve", t_solve)
        if tr:
            # the ONE extra host fetch of a traced solve (acceptance
            # contract: zero additional transfers per iteration)
            st.trace = self.last_trace = attempt_trace(res, tbuf)
        st.nsolves += 1
        st.niterations = niter
        st.ntotaliterations += niter
        st.bnrm2, st.x0nrm2, st.r0nrm2 = (
            first_norms if first_norms is not None
            else (float(res.bnrm2), float(res.x0nrm2), float(res.r0nrm2)))
        st.rnrm2 = float(res.rnrm2)
        st.dxnrm2 = float(res.dxnrm2)
        st.converged = bool(res.converged) or crit.unbounded
        if hl and aud is not None:
            # the health: section's audit summary + the acg_health_*
            # metrics + (threshold exceeded) the accuracy_degraded event
            from acg_tpu import health as health_mod
            health_mod.note_audit(st, aud, self.health_spec, "jax-cg",
                                  fresh=aud_fresh)
        # service-metrics tier: one completed solve (no-op disarmed;
        # the sharded subclass reuses this solve, so its comm ledger
        # rides through the same hook)
        from acg_tpu import metrics
        metrics.record_solve(t_solve, niter, st.converged,
                             solver=self._solver_name())
        metrics.observe_solver_comm(self, niter)
        self._account_ops(st, niter, dtype)
        if host_result:
            x = np.asarray(res.x)
            st.fexcept_arrays = [x]
        else:
            x = res.x
            # device-side scans; only two bools cross the wire.  The
            # sentinels reproduce the host report's NaN/Inf distinction
            # (errors.fexcept_str).
            has_nan = bool(jnp.isnan(res.x).any())
            has_inf = bool(jnp.isinf(res.x).any())
            st.fexcept_arrays = [np.asarray([np.nan if has_nan else 0.0,
                                             np.inf if has_inf else 0.0])]
        if not st.converged and raise_on_divergence:
            raise NotConvergedError(
                f"{niter} iterations, residual {st.rnrm2:.3e}")
        return x

    def _solver_name(self) -> str:
        """Telemetry/metrics label: the recurrence decides (the CA
        names deliberately avoid the 'pipelined' substring -- see
        recurrence.RecurrenceSpec.solver_name)."""
        if self.algo is not None:
            return self.algo.solver_name("cg")
        return "cg-pipelined" if self.pipelined else "cg"

    def _account_ops(self, st, niter: int, dtype) -> None:
        """Analytic flop/byte census of ``niter`` iterations on this
        configuration -- shared by the plain and checkpoint-chunked
        solve paths so their stats blocks cannot drift apart."""
        n = self.A.nrows
        per_it = cg_flops_per_iteration(self._spmv_flops / 3.0, n,
                                        self.pipelined)
        st.nflops += per_it * niter + self._spmv_flops + 2.0 * n
        dbl = np.dtype(dtype).itemsize
        # matrix bytes in the MATRIX storage dtype (they differ from the
        # vector dtype under --dtype mixed) + per-format index bytes
        mat_dbl = np.dtype(matrix_dtype(self.A)).itemsize
        idx_b = matrix_index_bytes(self.A)
        mat_bytes = int((self._spmv_flops / 3.0) * (mat_dbl + idx_b))
        if self.replace_every:
            # inner vectors are bf16 regardless of the (f32) outer dtype;
            # each segment adds one f32-vector replacement SpMV
            nseg = -(-niter // self.replace_every) if niter else 0
            st.nflops += self._spmv_flops * nseg
            vb = 2
            st.ops["gemv"].add(niter + nseg + 1, 0.0,
                               (mat_bytes + 2 * n * vb) * niter
                               + (mat_bytes + 2 * n * 4) * (nseg + 1))
            # carried-direction mode adds the (r, p) line-search dot per
            # iteration and a (p, p) health check per segment
            ndot = (2 * niter if self.replace_restart
                    else 3 * niter + nseg)
            st.ops["dot"].add(ndot, 0.0, 2 * n * vb * ndot)
            st.ops["axpy"].add(3 * niter, 0.0, 3 * n * vb * 3 * niter)
        elif (isinstance(self.kernels, str)
              and self.kernels.startswith("fused")):
            # both dots and all updates are folded into the two streamed
            # kernels: bill phase A (planes + r/p windows + p/t writes)
            # as gemv and phase B (4 reads + 2 writes) as axpy; nothing
            # re-reads vectors for dots
            st.ops["gemv"].add(niter + 1, 0.0,
                               (mat_bytes + 4 * n * dbl) * (niter + 1))
            st.ops["axpy"].add(niter, 0.0, 6 * n * dbl * niter)
        elif self.algo is not None:
            # communication-avoiding recurrences: s-step runs (2s-1)/s
            # SpMV-equivalents per iteration (the matrix-powers basis)
            # plus the Gram matmul and three map-back GEMVs per block;
            # p(l) runs 1 SpMV + the fused (2l+2)-window matvec per
            # iteration plus the v-recovery combination.  Billed as the
            # dominant op classes; flops fold the basis overhead
            from acg_tpu.recurrence import reduction_schedule
            sched = reduction_schedule(self.algo, False)
            spmv_eq = sched["spmv_per_iteration"]
            st.nflops += self._spmv_flops * (spmv_eq - 1.0) * niter
            st.ops["gemv"].add(int(niter * spmv_eq) + 1, 0.0,
                               int((mat_bytes + 2 * n * dbl)
                                   * (niter * spmv_eq + 1)))
            wred = sched["allreduce_scalars"]
            ndot = int(niter * sched["allreduce_per_iteration"])
            st.ops["dot"].add(max(ndot, 1), 0.0,
                              int(2 * n * dbl * wred ** 0.5
                                  * max(ndot, 1)))
            st.ops["nrm2"].add(niter + 1, 0.0, n * dbl * (niter + 1))
            st.ops["axpy"].add(3 * niter, 0.0, 3 * n * dbl * 3 * niter)
        else:
            # per-iteration op census matching the eager host solver's
            # (host_cg.solve): the convergence test's (r, r) is the nrm2
            # class -- niter in-loop + 1 at setup -- and classic CG's
            # p = r setup is the one copy.  These were the permanently-
            # zero rows of the compiled solvers' stats block (the
            # reference fills both, cgcuda.c:1942-1957).
            st.ops["gemv"].add(niter + 1, 0.0,
                               (mat_bytes + 2 * n * dbl) * (niter + 1))
            st.ops["dot"].add(niter, 0.0, 2 * n * dbl * niter)
            st.ops["nrm2"].add(niter + 1, 0.0, n * dbl * (niter + 1))
            st.ops["axpy"].add(3 * niter, 0.0, 3 * n * dbl * 3 * niter)
            if not self.pipelined:
                st.ops["copy"].add(1, 0.0, 2 * n * dbl)
            if self.precond_spec is not None:
                self._account_precond(st, niter, n, dbl, mat_bytes)

    def _account_precond(self, st, niter: int, n: int, dbl: int,
                         mat_bytes: int) -> None:
        """Analytic accounting for the preconditioner (the precond_apply
        satellite): niter + 1 applies per solve (setup z0 + one per
        iteration); cheby's op count bills its degree-many SpMVs per
        apply, the PCG scalar (r, z) adds one dot per apply, and the
        ``precond:`` stats section records the armed configuration."""
        from acg_tpu import metrics, precond as precond_mod

        spec = self.precond_spec
        nappl = niter + 1
        per_apply_flops = precond_mod.flops_per_apply(
            spec, n, self._spmv_flops)
        st.nflops += per_apply_flops * nappl
        sb = precond_mod.state_bytes(self._mstate)
        per_apply_bytes = precond_mod.bytes_per_apply(
            spec, n, dbl, mat_bytes + 2 * n * dbl, sb)
        nops = nappl * (spec.degree if spec.kind == "cheby" else 1)
        st.ops["precond"].add(nops, 0.0, int(per_apply_bytes * nappl))
        # the extra PCG scalar (r, z) per apply
        st.ops["dot"].add(nappl, 0.0, 2 * n * dbl * nappl)
        st.precond.update({
            "kind": str(spec),
            "applies": nappl,
            "flops_per_apply": per_apply_flops,
            "state_bytes": sb,
        })
        if spec.kind == "cheby":
            st.precond["lambda_min"] = float(self._mstate[0])
            st.precond["lambda_max"] = float(self._mstate[1])
        metrics.record_precond(spec.kind, nops)

    def _host_fallback(self, b, crit, raise_on_divergence: bool,
                       host_result: bool):
        """The last recovery rung: re-solve on the host reference solver
        (f64 numpy) from the ORIGINAL b -- the device state is junk by
        definition here.  Stats for the last solve reflect the host run;
        the op-class byte accounting keeps the device attempts."""
        from acg_tpu import faults
        from acg_tpu.solvers.host_cg import HostCGSolver
        from acg_tpu.solvers.resilience import adopt_host_stats

        hs = HostCGSolver(self.host_matrix)
        with faults.suppressed():
            x = hs.solve(np.asarray(b, np.float64), criteria=crit,
                         raise_on_divergence=raise_on_divergence)
        adopt_host_stats(self.stats, hs.stats)
        return x if host_result else jnp.asarray(x)

    # -- survivability tier: checkpoint-chunked solve ---------------------

    _ckpt_tier = "jax-cg"

    def _solve_ckpt(self, b, x0=None, criteria=None,
                    raise_on_divergence: bool = True, warmup: int = 0,
                    host_result: bool = True):
        """Checkpoint-armed solve (acg_tpu.checkpoint): the UNCHANGED
        direct program dispatched in host chunks of at most
        ``ckpt.every`` iterations with the full loop carry threaded
        through (``state_io``), a checksummed snapshot committed by
        atomic rename at every boundary, and detected breakdowns
        answered by the recovery ladder's new FIRST rung -- rollback to
        the last snapshot -- before the existing restart/fallback/abort
        ladder.  Because the carry continues the recurrence exactly,
        the chunked trajectory is iteration-identical to solve()'s
        (asserted in tests/test_checkpoint.py); snapshot time is billed
        to its own ``ckpt`` phase, never the solve."""
        from acg_tpu import checkpoint as ckpt_mod
        from acg_tpu import faults, metrics, observatory, telemetry, \
            tracing
        from acg_tpu import health as health_mod
        from acg_tpu._platform import (block_until_ready_works,
                                       device_sync)
        from acg_tpu.solvers.resilience import RecoveryDriver

        cfg = self.ckpt
        crit = criteria or StoppingCriteria()
        st = self.stats
        st.criteria = crit
        fault0 = faults.device_fault()
        self._fault_refusals(fault0)
        detect = self._detect(fault0)
        dtype = self._solve_dtype()
        sdt = acc_dtype(dtype)
        if fault0 is not None:
            telemetry.record_event(st, "fault-armed",
                                   f"{fault0.site}:{fault0.mode}"
                                   f"@{fault0.iteration}")
        t_xfer = time.perf_counter()
        with telemetry.annotate("transfer"):
            b_host = np.asarray(b, dtype=dtype)
            b_dev = jnp.asarray(b_host)
            x0_dev = (jnp.zeros_like(b_dev) if x0 is None
                      else jnp.asarray(x0, dtype=dtype))
        telemetry.add_timing(st, "transfer",
                             time.perf_counter() - t_xfer)
        b_crc = ckpt_mod.vector_checksum(b_host)
        program, base, kwargs, tr = self._select_program(
            b_dev, x0_dev, crit, detect=detect, fault=fault0)
        kwargs = dict(kwargs)
        kwargs["state_io"] = True
        hl = "health" in kwargs
        pc_kind = (str(self.precond_spec)
                   if self.precond_spec is not None else None)
        algo_name = str(self.algo) if self.algo is not None else None
        is_pl = self.algo is not None and self.algo.kind == "pl"
        if self.algo is not None:
            names = ckpt_mod.ca_carry_names(self.algo.kind)
            solver_name = f"cg-{self.algo.kind}"
        else:
            names = ckpt_mod.carry_names(self.pipelined,
                                         self.precond_spec is not None)
            solver_name = ("cg-pipelined" if self.pipelined else "cg")

        if self.algo is not None:
            # CA base is the 7-tuple (A, b, x0, atol, rtol, lam,
            # maxits): lam rides at base[5], there are no diff tols
            def chunk_args(x_dev, atol, rtol, m):
                return (base[0], base[1], x_dev,
                        jnp.asarray(atol, sdt), jnp.asarray(rtol, sdt),
                        base[5], jnp.int32(m))
        else:
            def chunk_args(x_dev, atol, rtol, m):
                return (base[0], base[1], x_dev,
                        jnp.asarray(atol, sdt), jnp.asarray(rtol, sdt),
                        base[5], base[6], jnp.int32(m))

        def pl_adv(carry):
            # the deep pipeline's advance counter rides IN the carry
            # (frame-absolute since the last restart): the chunk cap
            # and the per-chunk iteration count are both relative to it
            return int(jnp.asarray(carry[-1])) if carry is not None \
                else 0

        def run(a, carry, k0):
            # the chunk's starting trajectory iteration keeps the
            # health tier's audit/ABFT cadence phased to GLOBAL
            # iteration numbers (a dynamic arg: chunks never retrace)
            koff = jnp.int32(k0) if hl else None
            out = program(*a, carry=carry, k_offset=koff, **kwargs)
            i = 1
            ring = out[i] if tr else None
            i += 1 if tr else 0
            aud = out[i] if hl else None
            i += 1 if hl else 0
            return out[0], ring, aud, out[i]

        # -- resume reconstruction ------------------------------------
        consumed = 0          # trajectory iterations (incl. pre-crash)
        executed = 0          # iterations THIS process actually ran
        resumed_from = None
        carry = None
        x_cur = x0_dev
        abs_tol = None
        first_norms = None
        snap = cfg.resume
        repartitioned = None
        if snap is not None:
            ckpt_mod.validate_resume(
                snap, tier=self._ckpt_tier, pipelined=self.pipelined,
                precond=pc_kind, n=int(self.A.nrows), dtype=dtype,
                b_crc=b_crc, repartition=cfg.repartition,
                algorithm=algo_name)
            ckpt_mod.check_resume_env(snap, st)
            if cfg.repartition:
                # shape-portable resume: reassemble the carry into
                # global row order (an N-part snapshot's vectors come
                # back as plain length-n arrays -- this tier's native
                # layout); the recurrence continues with the same
                # global Krylov state, so convergence carries over up
                # to dot-product re-association
                snap, repartitioned = ckpt_mod.apply_repartition(
                    snap, tier=self._ckpt_tier, nparts=1, stats=st,
                    precond_spec=self.precond_spec)
            consumed = snap.iteration
            resumed_from = consumed
            sm = snap.meta
            abs_tol = float(sm["abs_tol"])
            first_norms = (float(sm["bnrm2"]), float(sm["x0nrm2"]),
                           float(sm["r0nrm2"]))
            x_cur = jnp.asarray(snap.arrays["x"])
            carry = tuple(jnp.asarray(snap.arrays[nm])
                          for nm in names[1:])
            metrics.record_resume()
            telemetry.record_event(
                st, "resume",
                f"resumed from snapshot at iteration {consumed}")
            sys.stderr.write(f"acg-tpu: {self._ckpt_tier}: resumed "
                             f"from snapshot at iteration "
                             f"{consumed}\n")
        last_snap = ((consumed, {"x": np.asarray(x_cur),
                                 **{nm: np.asarray(leaf)
                                    for nm, leaf in zip(names[1:],
                                                        carry)}})
                     if carry is not None else None)

        driver = RecoveryDriver(self.recovery, st, self._ckpt_tier)
        block_until_ready_works()
        if warmup > 0:
            # ONE zero-iteration dispatch absorbs the chunk program's
            # compile outside the timed window (further chunk variants
            # -- the carry-armed retrace -- land in the solve phase)
            t_w = time.perf_counter()
            with telemetry.annotate("compile"):
                device_sync(run(chunk_args(x_cur, 0.0, 0.0, 0),
                                carry, consumed)[0].x)
            telemetry.add_timing(st, "compile",
                                 time.perf_counter() - t_w)

        unbounded = crit.unbounded
        fault = fault0
        seq = 0
        nsnaps = 0
        ck_secs = 0.0
        rate = None
        aud_fresh = True
        gap_tripped = False
        res = None
        t0 = time.perf_counter()
        with telemetry.annotate("solve"):
            while True:
                remaining = crit.maxits - consumed
                if remaining <= 0:
                    break
                m = min(cfg.chunk_for(rate), remaining)
                if (self.algo is not None
                        and self.algo.kind == "sstep" and m < remaining):
                    # block-boundary-aligned cadence: a non-final chunk
                    # must end where a block ends -- the carried
                    # (r, p, gamma) only EQUALS the monolithic
                    # trajectory there (mid-block, the basis/Gram
                    # state is live and classic-shaped state is stale)
                    s_ = int(self.algo.param)
                    m = min(remaining, max(s_, (m // s_) * s_))
                if is_pl:
                    # the pipeline's cap/advance counters are frame-
                    # absolute (they ride in the carry): cap this chunk
                    # at carry-advance + m
                    m_cap = pl_adv(carry) + m
                else:
                    m_cap = m
                if abs_tol is None:
                    a = chunk_args(x_cur, crit.residual_atol,
                                   crit.residual_rtol, m_cap)
                else:
                    # later chunks keep the FIRST attempt's absolute
                    # target (the recovery-restart convention: never
                    # re-baseline rtol against an already-small
                    # residual)
                    a = chunk_args(x_cur, abs_tol, 0.0, m_cap)
                if "fault" in kwargs:
                    # the pl counters never reset across chunks, so its
                    # injector already fires in the right frame --
                    # shifting would double-subtract
                    kwargs["fault"] = (fault if is_pl
                                       else fault.shift(executed)
                                       if fault is not None else None)
                t_chunk = time.time()
                adv_in = pl_adv(carry) if is_pl else 0
                res, tbuf, aud, core = run(a, carry, consumed)
                device_sync(res.x)
                t_end = time.time()
                k_chunk = int(res.niterations) - adv_in
                if k_chunk > 0:
                    # measured s/iteration sizes the next chunk under
                    # the wall-clock cadence (cfg.chunk_for)
                    rate = (t_end - t_chunk) / k_chunk
                # timeline tier: one span per chunked dispatch, named
                # by its trajectory window (no-op disarmed)
                tracing.record_span(
                    f"chunk k{consumed}..{consumed + k_chunk}",
                    t_chunk, t_end, cat="chunk",
                    k_offset=consumed, iterations=k_chunk)
                consumed += k_chunk
                executed += k_chunk
                if first_norms is None:
                    first_norms = (float(res.bnrm2), float(res.x0nrm2),
                                   float(res.r0nrm2))
                    abs_tol = max(crit.residual_atol,
                                  crit.residual_rtol * first_norms[2])
                if tr:
                    st.trace = self.last_trace = \
                        telemetry.ConvergenceTrace.from_ring(
                            np.asarray(tbuf), k_chunk,
                            solver=solver_name,
                            offset=consumed - k_chunk)
                # live-observatory tier: the per-chunk carry return is
                # a REAL mid-solve iteration/residual sample for the
                # status endpoint (no-op disarmed; host-side only, so
                # the compiled programs are untouched)
                observatory.note_chunk(
                    self._ckpt_tier, consumed, float(res.rnrm2),
                    abs_tol=abs_tol,
                    trace=(st.trace if tr else None),
                    rtol=crit.residual_rtol)
                if hl and aud is not None:
                    gap_tripped = health_mod.note_audit(
                        st, aud, self.health_spec, self._ckpt_tier,
                        fresh=aud_fresh)
                    aud_fresh = False
                if detect and bool(res.breakdown):
                    if tr:
                        driver.log_trace_window(st.trace)
                    if (gap_tripped
                            and self.health_spec.action == "abort"):
                        st.tsolve += time.perf_counter() - t0 - ck_secs
                        st.converged = False
                        from acg_tpu.errors import BreakdownError
                        raise BreakdownError(
                            f"{self._ckpt_tier}: true-residual gap "
                            f"{st.health.get('gap_max', 0.0):.3e} "
                            f"exceeds threshold "
                            f"{self.health_spec.threshold:g} at "
                            f"iteration {consumed} (--on-gap abort)")
                    driver.note_breakdown(consumed)
                    # a fault that fired (that is what broke the solve)
                    # must not deterministically re-fire after the
                    # rollback/restart; `fault` stays in the TRAJECTORY
                    # frame (the per-dispatch shift above rebases it),
                    # so vanish it once its iteration has executed --
                    # rebasing here would make the dispatch shift
                    # double-subtract a still-pending fault
                    if (fault is not None and fault.device_site
                            and (is_pl
                                 or fault.iteration <= executed)):
                        # pl: the injector frame is the pipeline's own
                        # counter, which a rollback/restart rewinds --
                        # a deterministic re-fire would livelock the
                        # ladder, so vanish it outright
                        fault = None
                    # FIRST RUNG: roll the carry back to the last
                    # committed snapshot -- exact pre-corruption Krylov
                    # state, restart budget untouched
                    if (last_snap is not None
                            and driver.on_rollback(consumed,
                                                   last_snap[0])):
                        arrs = last_snap[1]
                        x_cur = jnp.asarray(arrs["x"])
                        carry = tuple(jnp.asarray(arrs[nm])
                                      for nm in names[1:])
                        consumed = last_snap[0]
                        continue
                    # second rung: restart from the recomputed true
                    # residual (carry=None re-enters the setup path)
                    if driver.on_breakdown(consumed, noted=True):
                        x_next = res.x
                        if not bool(jnp.isfinite(x_next).all()):
                            driver.record("iterate non-finite; "
                                          "restarting from the "
                                          "initial guess")
                            x_next = x0_dev
                        if self.precond_spec is not None:
                            from acg_tpu.precond import refresh_state
                            if refresh_state(self, driver):
                                kwargs["mstate"] = self._mstate
                        x_cur = x_next
                        carry = None
                        continue
                    pol = self.recovery
                    if (pol is not None and pol.fallback_host
                            and self.host_matrix is not None):
                        driver.on_fallback(
                            "fallback: host reference solver")
                        st.tsolve += time.perf_counter() - t0 - ck_secs
                        return self._host_fallback(
                            b_host, crit, raise_on_divergence,
                            host_result)
                    st.tsolve += time.perf_counter() - t0 - ck_secs
                    st.converged = False
                    raise driver.give_up(
                        consumed, float(res.rnrm2),
                        snapshot=cfg.path if nsnaps else None)
                finished = (consumed >= crit.maxits if unbounded
                            else bool(res.converged))
                x_cur = res.x
                carry = core
                if cfg.path is not None and not finished:
                    t_ck = time.perf_counter()
                    arrs = {"x": np.asarray(res.x)}
                    for nm, leaf in zip(names[1:], core):
                        arrs[nm] = np.asarray(leaf)
                    seq += 1
                    meta = {
                        "tier": self._ckpt_tier,
                        "pipelined": bool(self.pipelined),
                        "algorithm": algo_name,
                        "precond": pc_kind,
                        "n": int(self.A.nrows),
                        "dtype": str(np.dtype(dtype)),
                        "iteration": consumed,
                        "seq": seq,
                        "abs_tol": float(abs_tol),
                        "bnrm2": first_norms[0],
                        "x0nrm2": first_norms[1],
                        "r0nrm2": first_norms[2],
                        "b_crc": b_crc,
                        "fault": (str(faults.active_fault())
                                  if faults.active_fault() is not None
                                  else None),
                        "trace_tail": ckpt_mod.trace_tail(
                            st.trace if tr else None),
                    }
                    ckpt_mod.agree_seq(seq, consumed)
                    nbytes = ckpt_mod.save_snapshot(cfg.path, meta,
                                                    arrs)
                    dt = time.perf_counter() - t_ck
                    ck_secs += dt
                    telemetry.add_timing(st, "ckpt", dt)
                    metrics.record_snapshot(nbytes, dt)
                    nsnaps += 1
                    last_snap = (consumed, arrs)
                    # the crash:exit site models preemption BETWEEN
                    # iterations, after the snapshot committed
                    faults.maybe_crash(consumed - k_chunk, consumed)
                if finished:
                    break
        if res is None:
            # a resumed snapshot already at (or past) the iteration
            # cap: no chunk ever ran -- nothing sensible to report
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                f"snapshot iteration {consumed} already meets the "
                f"iteration cap {crit.maxits}; raise --max-iterations "
                f"to continue this solve")
        t_solve = time.perf_counter() - t0 - ck_secs
        st.tsolve += t_solve
        telemetry.add_timing(st, "solve", t_solve)
        st.nsolves += 1
        st.niterations = executed
        st.ntotaliterations += executed
        st.bnrm2, st.x0nrm2, st.r0nrm2 = first_norms
        st.rnrm2 = float(res.rnrm2)
        st.dxnrm2 = float(res.dxnrm2)
        st.converged = bool(res.converged) or crit.unbounded
        st.ckpt = {
            "path": cfg.path,
            "every": int(cfg.every),
            "snapshots": nsnaps,
            "iteration": consumed,
            "rollbacks": driver.rollbacks,
        }
        if cfg.secs > 0:
            st.ckpt["secs"] = float(cfg.secs)
        if resumed_from is not None:
            st.ckpt["resumed_from"] = resumed_from
        if repartitioned is not None:
            st.ckpt["repartitioned_from"] = repartitioned
        metrics.record_solve(t_solve, executed, st.converged,
                             solver=solver_name)
        metrics.observe_solver_comm(self, executed)
        self._account_ops(st, executed, dtype)
        if host_result:
            x = np.asarray(res.x)
            st.fexcept_arrays = [x]
        else:
            x = res.x
            has_nan = bool(jnp.isnan(res.x).any())
            has_inf = bool(jnp.isinf(res.x).any())
            st.fexcept_arrays = [np.asarray([np.nan if has_nan else 0.0,
                                             np.inf if has_inf
                                             else 0.0])]
        if not st.converged and raise_on_divergence:
            raise NotConvergedError(
                f"{executed} iterations, residual {st.rnrm2:.3e}")
        return x
