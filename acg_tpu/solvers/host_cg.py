"""Host reference CG solver (numpy, float64).

Rebuilds the reference's textbook host solver ``acg/cg.c`` (SURVEY.md
component #16): the correctness oracle for the accelerated paths, with all
four stopping criteria, per-op time/flop/byte statistics, and the same
update order as ``acgsolver_solve`` (``cg.c:198-407``):

    r0 = b - A x0;  p = r;  gamma = (r,r)
    repeat:  t = A p
             alpha = gamma / (p,t)
             x += alpha p;  r -= alpha t
             gamma' = (r,r);  beta = gamma'/gamma;  p = r + beta p

Convergence is tested on ||r|| (and optionally ||alpha p|| for the
difference-in-iterates criteria) every iteration, as in ``cg.c:318-368``.
"""

from __future__ import annotations

import time

import numpy as np
import scipy.sparse as sp

from acg_tpu.errors import IndefiniteMatrixError, NotConvergedError
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.solvers.stats import (SolverStats, StoppingCriteria,
                                   cg_flops_per_iteration)


def as_csr(A: SymCsrMatrix | sp.spmatrix, epsilon: float = 0.0) -> sp.csr_matrix:
    """Normalise a solver matrix argument to scipy CSR with the
    ``--epsilon`` diagonal shift applied (``symcsrmatrix.c:760-862``)."""
    if isinstance(A, SymCsrMatrix):
        return A.to_csr(epsilon)
    A = sp.csr_matrix(A)
    if epsilon:
        A = (A + epsilon * sp.eye(A.shape[0], format="csr")).tocsr()
    return A


class HostCGSolver:
    """Serial host CG over a :class:`SymCsrMatrix` (the ``acgsolver`` role).

    ``recovery`` (acg_tpu.solvers.resilience.RecoveryPolicy) arms
    breakdown detection -- non-finite residual or non-positive (p, Ap)
    -- with eager in-place restart: the true residual is recomputed from
    the last finite iterate and the Krylov space rebuilt, the same
    policy the compiled solvers run host-side.  Detection also arms
    while the fault injector (acg_tpu.faults) is active."""

    def __init__(self, A: SymCsrMatrix | sp.spmatrix, epsilon: float = 0.0,
                 recovery=None, trace: int = 0, progress: int = 0,
                 precond=None, health=None, ckpt=None):
        self.A = as_csr(A, epsilon)
        self.n = self.A.shape[0]
        # survivability tier (acg_tpu.checkpoint): the eager twin of
        # the compiled chunk drivers -- snapshots written in-loop every
        # ``ckpt.every`` iterations, breakdowns answered by the
        # rollback rung first
        if ckpt is not None:
            from acg_tpu.checkpoint import CheckpointConfig
            if not isinstance(ckpt, CheckpointConfig):
                raise ValueError("ckpt must be an acg_tpu.checkpoint."
                                 "CheckpointConfig or None")
        self.ckpt = ckpt
        self.nnz_full = self.A.nnz
        self.recovery = recovery
        # numerical-health tier (acg_tpu.health): the EAGER twin of the
        # compiled tiers' in-loop audit -- f64 arithmetic, so this
        # solver doubles as the ground-truth-gap oracle in the tests.
        # `replace` applies residual replacement literally (r := b - Ax
        # in place) instead of the compiled tiers' restart hand-off
        if health is not None and not getattr(health, "armed", False):
            health = None
        self.health_spec = health
        # preconditioning tier (acg_tpu.precond): the eager PCG twin of
        # the compiled solvers' -- same three kinds, f64 numpy/scipy
        # arithmetic (this solver doubles as the PCG oracle in tests)
        from acg_tpu.precond import parse_precond
        self.precond_spec = parse_precond(precond)
        self._mhost = None
        # telemetry tier (acg_tpu.telemetry): the eager twin of the
        # compiled solvers' device ring -- same (rnrm2, alpha, beta,
        # pAp) tuple, same capacity/wrap semantics, recorded per
        # iteration in plain Python
        self.trace = int(trace)
        self.progress = int(progress)
        self.last_trace = None
        self.stats = SolverStats(unknowns=self.n)

    def _op(self, name, t, n_bytes, flops):
        self.stats.ops[name].add(1, t, n_bytes)
        self.stats.nflops += flops

    def solve(self, b: np.ndarray, x0: np.ndarray | None = None,
              criteria: StoppingCriteria | None = None,
              raise_on_divergence: bool = True) -> np.ndarray:
        crit = criteria or StoppingCriteria()
        st = self.stats
        st.criteria = crit
        A, n = self.A, self.n
        b = np.asarray(b, dtype=np.float64)
        x = np.array(x0, dtype=np.float64, copy=True) if x0 is not None else np.zeros(n)
        dbl = 8
        from acg_tpu import faults
        fault = faults.device_fault()
        _spec_all = faults.active_fault()
        if (_spec_all is not None and _spec_all.site == "crash"
                and (self.ckpt is None or self.ckpt.path is None)):
            from acg_tpu.errors import AcgError, ErrorCode
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                "crash:exit fires between snapshot commits; arm "
                "--ckpt FILE --ckpt-every K (a crash with no snapshot "
                "to resume from proves nothing)")
        if fault is not None and (fault.site == "halo" or fault.part > 0):
            from acg_tpu.errors import AcgError, ErrorCode
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                "the serial host solver has no halo and only part 0: "
                "this fault spec could never fire")
        if (fault is not None and fault.site == "precond"
                and self.precond_spec is None):
            from acg_tpu.errors import AcgError, ErrorCode
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                "precond fault injection needs an armed preconditioner "
                "(--precond jacobi|bjacobi|cheby:K); this solve runs "
                "unpreconditioned CG")
        M = None
        if self.precond_spec is not None:
            if self._mhost is None:
                from acg_tpu.precond import HostPrecond
                self._mhost = HostPrecond(self.precond_spec, A)
            M = self._mhost
            from acg_tpu.precond import (bytes_per_apply, flops_per_apply,
                                         state_bytes)
            self._mflops = flops_per_apply(self.precond_spec, self.n,
                                           3.0 * self.nnz_full)
            # kind-aware per-apply traffic (cheby streams the CSR
            # degree-many times), matching the compiled tiers' census
            self._mbytes = bytes_per_apply(
                self.precond_spec, self.n, 8,
                self.nnz_full * (8 + 4) + 2 * self.n * 8,
                state_bytes(M.state))
        pol = self.recovery
        # detection mirrors the device tiers' _detect: recovery, an
        # active injector, or a health spec whose detectors trip (the
        # replace/abort/stall actions route through the driver so the
        # restart budget and the resilience counters stay honest)
        detect = (pol is not None or fault is not None
                  or (self.health_spec is not None
                      and self.health_spec.arms_detect))
        driver = None
        if detect:
            from acg_tpu.solvers.resilience import RecoveryDriver
            driver = RecoveryDriver(pol, st, "host-cg")
        hspec = self.health_spec
        audited = hspec is not None and hspec.every > 0
        # audit bookkeeping mirroring the device tiers' carried vector
        h_gap, h_gap_max, h_naud, h_stall = float("nan"), 0.0, 0, 0
        # ABFT checksum bookkeeping (the eager Huang-Abraham twin):
        # column checksum c = A^T 1 = A 1 (symmetric), compared against
        # sum(A p) at the audit cadence with the device tiers' exact
        # mismatch scale
        abft_armed = hspec is not None and hspec.abft
        ab_rel, ab_max, ab_n, ab_trips = float("nan"), 0.0, 0, 0
        if abft_armed:
            from acg_tpu.health import abft_default_threshold
            cvec = A @ np.ones(n)
            ab_tau = (hspec.abft_threshold
                      or abft_default_threshold(np.float64, n))

        def aud_vec():
            """The device tiers' fetched audit vector, rebuilt from the
            eager counters (8 slots with ABFT armed, 4 without)."""
            base = [h_gap, h_gap_max, h_naud, h_stall]
            if abft_armed:
                base += [ab_rel, ab_max, ab_n, ab_trips]
            return base

        rr_prev = float("inf")
        recorder = None
        if self.trace:
            from acg_tpu.telemetry import EagerTraceRecorder
            recorder = EagerTraceRecorder(self.trace, audit=audited)

        def finish_trace():
            if recorder is not None:
                st.trace = self.last_trace = recorder.finish()
            return st.trace

        tstart = time.perf_counter()
        # st.timings["ckpt"] accumulates across solves on a shared
        # stats object; bill only THIS solve's snapshot seconds below
        ck_base = st.timings.get("ckpt", 0.0)
        st.bnrm2 = float(np.linalg.norm(b))
        st.x0nrm2 = float(np.linalg.norm(x))

        t0 = time.perf_counter()
        r = b - A @ x
        self._op("gemv", time.perf_counter() - t0,
                 self.nnz_full * (dbl + 4) + 2 * n * dbl, 3.0 * self.nnz_full)

        napply = [0]

        def papply(r, k=None):
            """One timed preconditioner apply (eager: seconds are real,
            unlike the compiled tiers' replayed estimates).  The op row
            counts per the compiled tiers' convention: cheby bills its
            degree-many SpMVs per apply, so host and device censuses
            agree."""
            t0 = time.perf_counter()
            z = M.apply(r)
            if fault is not None and k is not None:
                z = fault.apply_precond_np(z, k)
            napply[0] += 1
            per = (self.precond_spec.degree
                   if self.precond_spec.kind == "cheby" else 1)
            self.stats.ops["precond"].add(per, time.perf_counter() - t0,
                                          int(self._mbytes))
            self.stats.nflops += self._mflops
            return z

        if M is not None:
            z = papply(r)
            p = z.copy()
            gamma = float(r @ z)
            rr = float(r @ r)
            self._op("dot", 0.0, 2 * n * dbl, 2.0 * n)
        else:
            p = r.copy()
            gamma = rr = float(r @ r)
        self._op("copy", 0.0, 2 * n * dbl, 0.0)

        t0 = time.perf_counter()
        self._op("nrm2", time.perf_counter() - t0, n * dbl, 2.0 * n)
        st.r0nrm2 = st.rnrm2 = float(np.sqrt(rr))
        st.dxnrm2 = np.inf

        res_tol = max(crit.residual_atol,
                      crit.residual_rtol * st.r0nrm2)
        st.niterations = 0
        st.nsolves += 1
        converged = (not crit.unbounded) and self._test(crit, st, res_tol)
        k = 0

        # -- survivability tier: resume reconstruction + snapshot state
        ck = self.ckpt
        pc_kind = (str(self.precond_spec)
                   if self.precond_spec is not None else None)
        resumed_from = None
        nsnaps = 0
        last_snap = None
        if ck is not None and ck.resume is not None:
            from acg_tpu import checkpoint as ckpt_mod
            from acg_tpu import metrics as _m
            from acg_tpu.telemetry import record_event
            snap = ck.resume
            ckpt_mod.validate_resume(
                snap, tier="host-cg", pipelined=False, precond=pc_kind,
                n=n, dtype=np.float64,
                b_crc=ckpt_mod.vector_checksum(b),
                repartition=ck.repartition)
            ckpt_mod.check_resume_env(snap, st)
            if ck.repartition:
                # shape-portable resume: a stacked N-part snapshot
                # reassembles into the global row vectors this eager
                # oracle natively carries
                snap, _rep = ckpt_mod.apply_repartition(
                    snap, tier="host-cg", nparts=1, stats=st,
                    precond_spec=self.precond_spec)
            x = np.array(snap.arrays["x"], dtype=np.float64)
            r = np.array(snap.arrays["r"], dtype=np.float64)
            p = np.array(snap.arrays["p"], dtype=np.float64)
            gamma = float(snap.arrays["gamma"])
            rr = (float(snap.arrays["rr"]) if "rr" in snap.arrays
                  else gamma)
            k = resumed_from = snap.iteration
            sm = snap.meta
            # the FIRST attempt's absolute target and norms (never
            # re-baseline rtol against an already-small residual)
            res_tol = float(sm["abs_tol"])
            st.bnrm2 = float(sm["bnrm2"])
            st.x0nrm2 = float(sm["x0nrm2"])
            st.r0nrm2 = float(sm["r0nrm2"])
            st.rnrm2 = float(np.sqrt(rr))
            last_snap = (k, dict(snap.arrays))
            converged = ((not crit.unbounded)
                         and self._test(crit, st, res_tol))
            _m.record_resume()
            record_event(st, "resume",
                         f"resumed from snapshot at iteration {k}")

        # wall-clock cadence (ckpt_secs): time of the last commit
        last_commit = [time.perf_counter()]

        def _commit_snapshot():
            """One snapshot at the current iteration boundary (atomic
            rename, checkpoint.save_snapshot); billed to the 'ckpt'
            phase so solve latency stays clean."""
            nonlocal nsnaps, last_snap
            from acg_tpu import checkpoint as ckpt_mod
            from acg_tpu import metrics as _m
            from acg_tpu.telemetry import add_timing
            t_ck = time.perf_counter()
            last_commit[0] = t_ck
            arrs = {"x": x.copy(), "r": r.copy(), "p": p.copy(),
                    "gamma": np.float64(gamma)}
            if M is not None:
                arrs["rr"] = np.float64(rr)
            meta = {
                "tier": "host-cg", "pipelined": False,
                "precond": pc_kind, "n": int(n), "dtype": "float64",
                "iteration": int(k), "seq": nsnaps + 1,
                "abs_tol": float(res_tol),
                "bnrm2": st.bnrm2, "x0nrm2": st.x0nrm2,
                "r0nrm2": st.r0nrm2,
                "b_crc": ckpt_mod.vector_checksum(b),
                "fault": (str(faults.active_fault())
                          if faults.active_fault() is not None else None),
                "trace_tail": ckpt_mod.trace_tail(None),
            }
            nbytes = ckpt_mod.save_snapshot(ck.path, meta, arrs)
            dt = time.perf_counter() - t_ck
            add_timing(st, "ckpt", dt)
            _m.record_snapshot(nbytes, dt)
            prev = last_snap[0] if last_snap is not None else (
                resumed_from or 0)
            nsnaps += 1
            last_snap = (int(k), arrs)
            # crash:exit models preemption between iterations, after
            # the snapshot committed (crossing semantics: a resumed
            # solve starting at-or-past K does not re-kill itself)
            faults.maybe_crash(prev, k)

        def _breakdown(why: str):
            """Detected-breakdown recovery (eager twin of the compiled
            chunk drivers, same RecoveryDriver bookkeeping): FIRST roll
            the Krylov state back to the last snapshot when one exists;
            else recompute the true residual from the last finite
            iterate and rebuild the Krylov space; raise once the
            policy's restarts are exhausted."""
            nonlocal x, r, p, gamma, rr, M, k, fault
            driver.log_trace_window(finish_trace())
            driver.note_breakdown(k)
            # a deterministically-injected fault that already fired
            # must not re-fire after the rollback rewinds k
            if (fault is not None and fault.device_site
                    and fault.iteration < k):
                fault = None
            if (last_snap is not None
                    and driver.on_rollback(k, last_snap[0])):
                ks, arrs = last_snap
                x = np.array(arrs["x"])
                r = np.array(arrs["r"])
                p = np.array(arrs["p"])
                gamma = float(arrs["gamma"])
                rr = float(arrs.get("rr", gamma))
                k = ks
                st.rnrm2 = float(np.sqrt(rr))
                return
            if not driver.on_breakdown(k, noted=True):
                st.tsolve += time.perf_counter() - tstart
                st.converged = False
                st.fexcept_arrays = [x, r]
                if hspec is not None:
                    # the audits that ran must reach the health
                    # surfaces on exactly the failing solves
                    from acg_tpu.health import note_audit
                    note_audit(st, aud_vec(), hspec, "host-cg")
                raise driver.give_up(
                    k, st.rnrm2,
                    snapshot=(ck.path if ck is not None and nsnaps
                              else None))
            if not np.isfinite(x).all():
                x = (np.array(x0, dtype=np.float64, copy=True)
                     if x0 is not None else np.zeros(n))
                driver.record("iterate non-finite; restarting from the "
                              "initial guess")
            r = b - A @ x
            if M is not None:
                # preserve-or-rebuild (the compiled tiers' contract):
                # immutable finite state survives; a poisoned one is
                # refactored from the matrix
                if not all(np.isfinite(np.asarray(leaf)).all()
                           for leaf in M.state):
                    from acg_tpu.precond import HostPrecond
                    self._mhost = M = HostPrecond(self.precond_spec, A)
                    driver.record(f"preconditioner "
                                  f"({self.precond_spec}) state "
                                  f"non-finite; rebuilt from the matrix")
                else:
                    driver.record(f"preconditioner "
                                  f"({self.precond_spec}) state "
                                  f"preserved across restart")
                z = M.apply(r)
                p = z.copy()
                gamma = float(r @ z)
                rr = float(r @ r)
            else:
                p = r.copy()
                gamma = rr = float(r @ r)
            st.rnrm2 = float(np.sqrt(rr))

        while not converged and k < crit.maxits:
            t0 = time.perf_counter()
            t = A @ p
            if fault is not None:
                t = fault.apply_spmv_np(t, k)
            self._op("gemv", time.perf_counter() - t0,
                     self.nnz_full * (dbl + 4) + 2 * n * dbl, 3.0 * self.nnz_full)

            if abft_armed and (k + 1) % hspec.every == 0:
                # the eager Huang-Abraham check of THIS iteration's
                # t = A p: sum(t) vs (c, p), the device tiers' exact
                # mismatch scale -- a sign-flipped element (sdc:flip)
                # is finite, so only this test can see it
                ssum, cp, tt = float(t.sum()), float(cvec @ p), float(t @ t)
                denom = (np.sqrt(max(tt, 0.0) * n) + abs(ssum) + abs(cp)
                         + np.finfo(np.float64).tiny)
                rel = abs(ssum - cp) / denom
                ab_rel, ab_n = rel, ab_n + 1
                ab_max = max(ab_max, rel)
                if rel > ab_tau:
                    ab_trips += 1
                    k += 1
                    st.niterations = k
                    st.ntotaliterations += 1
                    _breakdown("ABFT checksum mismatch")
                    converged = self._test(crit, st, res_tol)
                    continue

            t0 = time.perf_counter()
            pdott = float(p @ t)
            if fault is not None:
                pdott = fault.apply_dot_np(pdott, k)
            self._op("dot", time.perf_counter() - t0, 2 * n * dbl, 2.0 * n)
            if detect and (not np.isfinite(pdott)
                           or (pdott <= 0.0 and gamma > 0.0)):
                k += 1
                st.niterations = k
                st.ntotaliterations += 1
                if recorder is not None:
                    # the poisoned scalar stays visible in the window
                    # the recovery log quotes; no update ran -> no
                    # alpha/beta for this iteration (preconditioned
                    # norm under precond, the compiled rings' slot)
                    gq = gamma if M is not None else st.rnrm2 ** 2
                    recorder.record(np.sqrt(gq) if gq >= 0 else gq,
                                    np.nan, np.nan, pdott)
                _breakdown("non-finite or non-positive p^T A p")
                converged = self._test(crit, st, res_tol)
                continue
            if pdott == 0.0:
                if gamma == 0.0:
                    # r = p = 0: exactly converged (reachable in
                    # fixed-iteration mode past convergence); iterating
                    # further is a 0/0, not an indefiniteness
                    break
                # (p, Ap) == 0 for p != 0: not positive definite; abort
                # like the reference (cg.c:304) instead of dividing
                st.tsolve += time.perf_counter() - tstart
                st.converged = False
                st.fexcept_arrays = [x, r]
                finish_trace()
                raise IndefiniteMatrixError(
                    f"(p, Ap) = 0 at iteration {k}")
            alpha = gamma / pdott

            t0 = time.perf_counter()
            x += alpha * p
            r -= alpha * t
            self._op("axpy", time.perf_counter() - t0, 3 * n * dbl, 2.0 * n)
            self._op("axpy", 0.0, 3 * n * dbl, 2.0 * n)

            if M is not None:
                z = papply(r, k)
                t0 = time.perf_counter()
                gamma_next = float(r @ z)
                rr = float(r @ r)
                self._op("dot", time.perf_counter() - t0, 2 * n * dbl,
                         2.0 * n)
                self._op("nrm2", 0.0, n * dbl, 2.0 * n)
            else:
                t0 = time.perf_counter()
                gamma_next = rr = float(r @ r)
                self._op("nrm2", time.perf_counter() - t0, n * dbl,
                         2.0 * n)
            if detect and (not np.isfinite(gamma_next)
                           or not np.isfinite(rr)
                           # a negative (r, z): the non-SPD-M signal
                           or (M is not None and gamma_next < 0)
                           # sign anomaly under the health tier: a
                           # negative computed (r, r) is arithmetic
                           # poison (device-tier rationale)
                           or (hspec is not None and gamma_next < 0)):
                k += 1
                st.niterations = k
                st.ntotaliterations += 1
                if recorder is not None:
                    # the compiled rings record the PRECONDITIONED
                    # residual norm under precond (the raw poisoned
                    # gamma stays visible); mirror them exactly
                    gq = gamma_next if M is not None else rr
                    recorder.record(np.sqrt(gq) if gq >= 0 else gq,
                                    alpha, np.nan, pdott)
                _breakdown("non-finite residual"
                           if not np.isfinite(rr)
                           else "non-SPD preconditioner signal")
                converged = self._test(crit, st, res_tol)
                continue
            gap = float("nan")
            if audited and (k + 1) % hspec.every == 0:
                # the eager twin of the device audit: true residual in
                # f64 through the same CSR, gap relative to ||b||
                rt = b - A @ x
                gap = (float(np.linalg.norm(rt - r))
                       / max(st.bnrm2, 1e-300))
                h_gap, h_naud = gap, h_naud + 1
                h_gap_max = max(h_gap_max, gap)
                if hspec.threshold and gap > hspec.threshold:
                    if hspec.action == "abort":
                        st.tsolve += time.perf_counter() - tstart
                        st.converged = False
                        st.fexcept_arrays = [x, r]
                        finish_trace()
                        from acg_tpu.errors import BreakdownError
                        from acg_tpu.health import note_audit
                        note_audit(st, aud_vec(), hspec, "host-cg")
                        raise BreakdownError(
                            f"host-cg: true-residual gap {gap:.3e} "
                            f"exceeds threshold {hspec.threshold:g} at "
                            f"iteration {k} (--on-gap abort)")
                    if hspec.action == "replace":
                        # residual replacement, applied literally (Van
                        # der Vorst & Ye): the recurrence residual is
                        # swapped for the true one -- but BOUNDED by
                        # the same restart budget the compiled tiers
                        # consume, and counted on the same resilience
                        # counters (driver.on_breakdown), so the
                        # cross-tier stats stay comparable and a
                        # hair-trigger threshold cannot loop forever
                        if not driver.on_breakdown(k):
                            st.tsolve += time.perf_counter() - tstart
                            st.converged = False
                            st.fexcept_arrays = [x, r]
                            finish_trace()
                            from acg_tpu.errors import BreakdownError
                            from acg_tpu.health import note_audit
                            note_audit(st, aud_vec(), hspec, "host-cg")
                            raise BreakdownError(
                                f"host-cg: true-residual gap {gap:.3e} "
                                f"exceeds threshold "
                                f"{hspec.threshold:g} at iteration "
                                f"{k} (--on-gap replace); "
                                f"{st.nrestarts} restart(s) exhausted "
                                f"and no fallback available")
                        st.recovery_log.append(
                            f"residual replacement at iteration {k}: "
                            f"gap {gap:.3e} > {hspec.threshold:g}")
                        r = rt
                        if M is not None:
                            z = papply(r)
                            gamma_next = float(r @ z)
                        else:
                            gamma_next = float(r @ r)
                        rr = float(r @ r)
            if hspec is not None and hspec.stall_window:
                h_stall = 0 if rr < rr_prev else h_stall + 1
                if h_stall >= hspec.stall_window:
                    # the stagnation detector feeds the breakdown path
                    # (an armed stall window always arms the driver --
                    # see the detect computation above), so restarts,
                    # counters, and the give-up raise match the
                    # compiled tiers'
                    k += 1
                    st.niterations = k
                    st.ntotaliterations += 1
                    st.rnrm2 = float(np.sqrt(rr)) if rr >= 0 else rr
                    h_stall = 0
                    _breakdown(f"stagnation: {hspec.stall_window} "
                               f"non-decreasing iterations")
                    converged = self._test(crit, st, res_tol)
                    continue
            rr_prev = rr
            beta = gamma_next / gamma
            gamma = gamma_next
            if crit.needs_diff:
                # ||x_{k+1} - x_k|| = |alpha| * ||p_k|| (the pre-update p)
                st.dxnrm2 = abs(alpha) * float(np.linalg.norm(p))

            t0 = time.perf_counter()
            p = (z if M is not None else r) + beta * p
            self._op("axpy", time.perf_counter() - t0, 3 * n * dbl, 2.0 * n)

            k += 1
            st.niterations = k
            st.ntotaliterations += 1
            st.rnrm2 = float(np.sqrt(rr))
            if recorder is not None:
                # the eager-twin contract: under precond the compiled
                # rings record the PRECONDITIONED norm sqrt((r, z)) in
                # the rnrm2 slot -- record the same quantity here (and
                # this iteration's audit gap in the gap column)
                gq = gamma if M is not None else rr
                recorder.record(float(np.sqrt(gq)) if gq >= 0 else gq,
                                alpha, beta, pdott, gap=gap)
            if self.progress and k % self.progress == 0:
                import sys

                # the observatory's shared heartbeat line: the oracle
                # path prints the same iterations/sec + ETA shape the
                # compiled loops' callback does, and feeds the status
                # endpoint the same samples
                from acg_tpu import observatory
                sys.stderr.write(observatory.heartbeat_line(
                    "host-cg", k, st.rnrm2) + "\n")
            if not crit.unbounded:
                converged = self._test(crit, st, res_tol)
            if (ck is not None and ck.path is not None and not converged
                    and k < crit.maxits):
                due = (k % ck.every == 0 if ck.every > 0
                       else time.perf_counter() - last_commit[0]
                       >= ck.secs)
                if due:
                    _commit_snapshot()

        t_solve = time.perf_counter() - tstart
        # snapshot serialisation is billed to its own phase, never the
        # solve (the compiled chunk drivers' convention)
        t_solve -= st.timings.get("ckpt", 0.0) - ck_base
        st.tsolve += t_solve
        from acg_tpu.telemetry import add_timing
        add_timing(st, "solve", t_solve)
        st.converged = converged or crit.unbounded
        if ck is not None:
            # niterations reports iterations THIS process executed (the
            # compiled chunk drivers' convention); the trajectory
            # iteration lives in the ckpt section
            if resumed_from is not None:
                st.niterations = max(k - resumed_from, 0)
            st.ckpt = {
                "path": ck.path,
                "every": int(ck.every),
                "snapshots": nsnaps,
                "iteration": int(k),
                "rollbacks": driver.rollbacks if driver is not None else 0,
            }
            if ck.secs > 0:
                st.ckpt["secs"] = float(ck.secs)
            if resumed_from is not None:
                st.ckpt["resumed_from"] = resumed_from
        if hspec is not None:
            from acg_tpu.health import note_audit
            note_audit(st, aud_vec(), hspec, "host-cg")
        from acg_tpu import metrics
        metrics.record_solve(t_solve, st.niterations, st.converged,
                             solver="host-cg")
        if M is not None:
            per = (self.precond_spec.degree
                   if self.precond_spec.kind == "cheby" else 1)
            st.precond.update({"kind": str(self.precond_spec),
                               "applies": napply[0],
                               "flops_per_apply": self._mflops})
            if self.precond_spec.kind == "cheby":
                st.precond["lambda_min"] = float(M.state[0])
                st.precond["lambda_max"] = float(M.state[1])
            metrics.record_precond(self.precond_spec.kind,
                                   napply[0] * per)
        st.fexcept_arrays = [x, r]
        finish_trace()
        if not st.converged and raise_on_divergence:
            raise NotConvergedError(
                f"{k} iterations, residual {st.rnrm2:.3e} > {res_tol:.3e}")
        return x

    @staticmethod
    def _test(crit: StoppingCriteria, st: SolverStats, res_tol: float) -> bool:
        if res_tol > 0 and st.rnrm2 < res_tol:
            return True
        if crit.diff_atol > 0 and st.dxnrm2 < crit.diff_atol:
            return True
        if crit.diff_rtol > 0 and st.dxnrm2 < crit.diff_rtol * max(st.x0nrm2, 1e-300):
            return True
        return False


class NativeHostCGSolver:
    """Host CG through the native C++ core (``native/src/cg.cpp``).

    The reference's host solver is native C (``acg/cg.c``); this is its
    direct counterpart -- same recurrences and stopping criteria as
    :class:`HostCGSolver` (the two oracles cross-check each other in the
    tests), with the OpenMP SpMV loop running at C speed.
    """

    def __init__(self, A: SymCsrMatrix | sp.spmatrix, epsilon: float = 0.0):
        from acg_tpu import _native

        if not _native.available():
            raise RuntimeError(
                "native core unavailable (build native/libacg_core.so or "
                "use --solver host)")
        self._native = _native
        self.A = as_csr(A, epsilon)
        self.n = self.A.shape[0]
        self.nnz_full = self.A.nnz
        self.stats = SolverStats(unknowns=self.n)

    def solve(self, b: np.ndarray, x0: np.ndarray | None = None,
              criteria: StoppingCriteria | None = None,
              raise_on_divergence: bool = True) -> np.ndarray:
        crit = criteria or StoppingCriteria()
        st = self.stats
        st.criteria = crit
        A, n = self.A, self.n
        b = np.asarray(b, dtype=np.float64)

        tstart = time.perf_counter()
        (x, r, niter, rnrm2, r0nrm2, dxnrm2, converged,
         indefinite) = self._native.cg_solve(
            A.indptr, A.indices, A.data, b, x0, crit.maxits,
            crit.residual_atol, crit.residual_rtol,
            crit.diff_atol, crit.diff_rtol)
        st.tsolve += time.perf_counter() - tstart

        st.nsolves += 1
        st.niterations = niter
        st.ntotaliterations += niter
        st.bnrm2 = float(np.linalg.norm(b))
        st.x0nrm2 = float(np.linalg.norm(x0)) if x0 is not None else 0.0
        st.r0nrm2, st.rnrm2 = r0nrm2, rnrm2
        st.dxnrm2 = dxnrm2
        st.converged = converged
        dbl = 8
        st.nflops += (cg_flops_per_iteration(self.nnz_full, n) * niter
                      + 3.0 * self.nnz_full + 2.0 * n)
        st.ops["gemv"].add(niter + 1, 0.0,
                           (self.nnz_full * (dbl + 8) + 2 * n * dbl)
                           * (niter + 1))
        st.ops["dot"].add(2 * niter, 0.0, 2 * n * dbl * 2 * niter)
        st.ops["axpy"].add(3 * niter, 0.0, 3 * n * dbl * 3 * niter)
        # scan x AND the final residual, like HostCGSolver: a NaN/Inf
        # present only in r must not go unreported
        st.fexcept_arrays = [x, r]
        if indefinite:
            raise IndefiniteMatrixError(f"(p, Ap) = 0 at iteration {niter}")
        if not converged and raise_on_divergence:
            raise NotConvergedError(
                f"{niter} iterations, residual {rnrm2:.3e}")
        return x


class HostDistCGSolver:
    """Distributed host CG over subdomains (``acgsolver_solvempi``,
    ``cg.c:408``), single-controller: per-part ghost-aware
    :class:`~acg_tpu.vector.PVector` BLAS-1 with reductions summed across
    parts (the ``MPI_Allreduce`` role) and halo exchange through
    :func:`~acg_tpu.graph.halo_exchange_host`.  The host-side oracle for
    the device :class:`~acg_tpu.parallel.dist.DistCGSolver` -- same data
    layout, no device, no XLA.
    """

    def __init__(self, subs):
        from acg_tpu.graph import Subdomain  # noqa: F401 (doc reference)
        self.subs = subs
        self.n = sum(s.nowned for s in subs)
        self.nnz_total = sum(int(s.A_local.nnz + s.A_ghost.nnz) for s in subs)
        self.stats = SolverStats(unknowns=self.n)

    def _spmv(self, ps):
        """Distributed SpMV: halo(p) then local + off-diagonal blocks
        (``acgsymcsrmatrix_dsymvmpi``, ``symcsrmatrix.c:1353-1397``)."""
        from acg_tpu.graph import dsymv_dist_host
        return dsymv_dist_host(self.subs, [p.data for p in ps])

    def solve(self, b_global: np.ndarray, x0: np.ndarray | None = None,
              criteria: StoppingCriteria | None = None,
              raise_on_divergence: bool = True) -> np.ndarray:
        from acg_tpu.graph import gather_vector, scatter_vector
        from acg_tpu.vector import PVector

        crit = criteria or StoppingCriteria()
        st = self.stats
        st.criteria = crit
        subs = self.subs
        b_global = np.asarray(b_global, dtype=np.float64)

        def pvecs(global_vec):
            return [PVector(v, s.nghost) for s, v in
                    zip(subs, scatter_vector(subs, global_vec))]

        def gdot(us, vs):
            return float(sum(u.dot(v) for u, v in zip(us, vs)))

        bs = pvecs(b_global)
        xs = pvecs(np.asarray(x0, dtype=np.float64) if x0 is not None
                   else np.zeros(self.n))

        tstart = time.perf_counter()
        st.bnrm2 = float(np.sqrt(gdot(bs, bs)))
        st.x0nrm2 = float(np.sqrt(gdot(xs, xs)))
        ts = self._spmv(xs)
        rs = [PVector(b.owned - t, 0) for b, t in zip(bs, ts)]
        ps = [PVector(np.concatenate([r.owned, np.zeros(s.nghost)]), s.nghost)
              for r, s in zip(rs, subs)]
        gamma = gdot(rs, rs)
        st.r0nrm2 = st.rnrm2 = float(np.sqrt(gamma))
        st.dxnrm2 = np.inf
        res_tol = max(crit.residual_atol, crit.residual_rtol * st.r0nrm2)
        st.niterations = 0
        st.nsolves += 1
        converged = (not crit.unbounded) and HostCGSolver._test(
            crit, st, res_tol)
        k = 0
        while not converged and k < crit.maxits:
            ts = self._spmv(ps)
            tvs = [PVector(t, 0) for t in ts]
            pdott = float(sum(np.dot(p.owned, t) for p, t in zip(ps, ts)))
            alpha = gamma / pdott
            if crit.needs_diff:
                st.dxnrm2 = abs(alpha) * float(
                    np.sqrt(gdot(ps, ps)))
            for x, r, p, t in zip(xs, rs, ps, tvs):
                x.axpy(alpha, p)
                r.axpy(-alpha, t)
            gamma_next = gdot(rs, rs)
            beta = gamma_next / gamma
            gamma = gamma_next
            for p, r in zip(ps, rs):
                p.aypx(beta, r)
            k += 1
            st.niterations = k
            st.ntotaliterations += 1
            st.rnrm2 = float(np.sqrt(gamma))
            if not crit.unbounded:
                converged = HostCGSolver._test(crit, st, res_tol)

        st.tsolve += time.perf_counter() - tstart
        st.converged = converged or crit.unbounded
        st.nflops += (3.0 * self.nnz_total + 10.0 * self.n) * max(k, 1)
        x = gather_vector(subs, [x.data for x in xs], self.n)
        st.fexcept_arrays = [x]
        if not st.converged and raise_on_divergence:
            raise NotConvergedError(
                f"{k} iterations, residual {st.rnrm2:.3e} > {res_tol:.3e}")
        return x


# -- batched/block eager oracles (the ground-truth parity targets) --------

def host_batched_cg(A, B, x0=None, criteria: StoppingCriteria | None = None
                    ) -> tuple:
    """Eager f64 multi-RHS twin of the batched device tier: the classic
    recurrence run per COLUMN (a plain numpy loop -- no fusion, no
    masks, the un-clever reference), so the device batched/block
    results have a ground-truth parity target whose arithmetic is
    beyond suspicion.  Returns ``(X, niterations, rnrm2)`` with
    per-RHS arrays."""
    crit = criteria or StoppingCriteria()
    A = as_csr(A)
    B = np.asarray(B, dtype=np.float64)
    if B.ndim == 1:
        B = B[:, None]
    n, nrhs = B.shape
    X = (np.zeros((n, nrhs)) if x0 is None
         else np.array(x0, dtype=np.float64, copy=True))
    iters = np.zeros(nrhs, dtype=np.int64)
    rn = np.zeros(nrhs)
    for j in range(nrhs):
        x = X[:, j].copy()
        r = B[:, j] - A @ x
        p = r.copy()
        gamma = float(r @ r)
        res_tol = max(crit.residual_atol,
                      crit.residual_rtol * np.sqrt(gamma))
        k = 0
        while (crit.unbounded or gamma >= res_tol * res_tol) \
                and k < crit.maxits:
            t = A @ p
            alpha = gamma / float(p @ t)
            x += alpha * p
            r -= alpha * t
            gamma_next = float(r @ r)
            beta = gamma_next / gamma
            gamma = gamma_next
            p = r + beta * p
            k += 1
        X[:, j] = x
        iters[j] = k
        rn[j] = np.sqrt(gamma)
    return X, iters, rn


def host_block_cg(A, B, x0=None, criteria: StoppingCriteria | None = None
                  ) -> tuple:
    """Eager f64 TRUE block-CG oracle (O'Leary 1980): one shared Krylov
    block, B x B Gram solves per iteration, rank deflation by relative
    Tikhonov jitter -- the same recurrence the device block tier
    compiles (acg_tpu.solvers.batched._block_cg_program), in plain
    numpy so its iteration counts and solutions anchor the acceptance
    tests.  Returns ``(X, niterations, rnrm2, block_iterations)``."""
    crit = criteria or StoppingCriteria()
    A = as_csr(A)
    B = np.asarray(B, dtype=np.float64)
    if B.ndim == 1:
        B = B[:, None]
    n, nrhs = B.shape
    X = (np.zeros((n, nrhs)) if x0 is None
         else np.array(x0, dtype=np.float64, copy=True))
    eps = np.finfo(np.float64).eps

    def deflated_solve(M, G):
        tr = np.trace(M) / M.shape[0]
        jitter = 64.0 * eps * max(abs(tr), eps)
        return np.linalg.solve(M + jitter * np.eye(M.shape[0]), G)

    R = B - A @ X
    rr = np.einsum("nb,nb->b", R, R)
    res_tol = np.maximum(crit.residual_atol,
                         crit.residual_rtol * np.sqrt(rr))
    done = (np.zeros(nrhs, bool) if crit.unbounded
            else rr < res_tol * res_tol)
    iters = np.zeros(nrhs, dtype=np.int64)
    P = R.copy()
    G = R.T @ R
    k = 0
    while k < crit.maxits and not done.all():
        Q = A @ P
        W = P.T @ Q
        alpha = deflated_solve(W, G)
        X = X + P @ alpha
        R = R - Q @ alpha
        rr = np.einsum("nb,nb->b", R, R)
        iters += (~done).astype(np.int64)
        if not crit.unbounded:
            done = done | (~done & (rr < res_tol * res_tol))
        G_new = R.T @ R
        beta = deflated_solve(G, G_new)
        P = R + P @ beta
        G = G_new
        k += 1
    return X, iters, np.sqrt(rr), k
