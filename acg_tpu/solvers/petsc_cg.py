"""External cross-implementation baseline solver (the PETSc KSPCG role).

The reference ships a PETSc-backed solver (``acg/cgpetsc.c:78-378``,
SURVEY.md component #21) as an *independent oracle*: a CG implementation
nobody in this codebase wrote, run over the same matrix, to cross-check
results and performance.  PETSc is not available in this environment; the
TPU build restores the role with ``scipy.sparse.linalg.cg`` -- an external,
independently-maintained CG (KSPCG analog; ``KSPPIPECG`` maps to the same
call, as scipy has no pipelined variant -- recorded in the stats header).

Same solve/stats contract as :class:`acg_tpu.solvers.host_cg.HostCGSolver`
so the CLI's ``--solver petsc`` slot (``cuda/acg-cuda.c:321-377``) drops in.
"""

from __future__ import annotations

import time

import numpy as np
import scipy.sparse as sp
import scipy.sparse.linalg as spla

from acg_tpu.errors import AcgError, ErrorCode, NotConvergedError
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.solvers.stats import SolverStats, StoppingCriteria


class PetscBaselineSolver:
    """scipy.sparse.linalg.cg over the assembled matrix (KSPCG analog)."""

    def __init__(self, A: SymCsrMatrix | sp.spmatrix, epsilon: float = 0.0,
                 pipelined: bool = False):
        from acg_tpu.solvers.host_cg import as_csr
        self.A = as_csr(A, epsilon)
        self.n = self.A.shape[0]
        self.pipelined = pipelined  # KSPPIPECG alias; same scipy call
        self.stats = SolverStats(unknowns=self.n)

    def solve(self, b: np.ndarray, x0: np.ndarray | None = None,
              criteria: StoppingCriteria | None = None,
              raise_on_divergence: bool = True) -> np.ndarray:
        crit = criteria or StoppingCriteria()
        if crit.needs_diff:
            raise AcgError(ErrorCode.INVALID_VALUE,
                           "--solver petsc supports residual criteria only "
                           "(as the reference's KSP convergence test)")
        st = self.stats
        st.criteria = crit
        A, n = self.A, self.n
        b = np.asarray(b, dtype=np.float64)
        x_init = (np.array(x0, dtype=np.float64, copy=True)
                  if x0 is not None else np.zeros(n))

        st.bnrm2 = float(np.linalg.norm(b))
        st.x0nrm2 = float(np.linalg.norm(x_init))
        r0 = b - A @ x_init
        st.r0nrm2 = float(np.linalg.norm(r0))

        # our criteria are relative to ||r0|| (cg.h:136-149); scipy's rtol
        # is relative to ||b||, so pass everything through atol
        res_tol = max(crit.residual_atol, crit.residual_rtol * st.r0nrm2)
        niters = 0

        def count(_xk):
            nonlocal niters
            niters += 1

        tstart = time.perf_counter()
        x, info = spla.cg(A, b, x0=x_init, rtol=0.0,
                          atol=res_tol if res_tol > 0 else 1e-300,
                          maxiter=crit.maxits, callback=count)
        elapsed = time.perf_counter() - tstart
        st.tsolve += elapsed

        r = b - A @ x
        st.rnrm2 = float(np.linalg.norm(r))
        st.dxnrm2 = np.inf
        st.nsolves += 1
        st.niterations = niters
        st.ntotaliterations += niters
        st.converged = (info == 0) or crit.unbounded
        # timing-only statistics, like the reference's PETSc slot
        # (KSPSolve wall time, cgpetsc.c:335-378): the analytic CG flop
        # count is real work and stays, but no per-op byte/time rows are
        # fabricated -- scipy's internals are not instrumented here
        st.nflops += (3.0 * self.A.nnz + 10.0 * n) * max(niters, 1)
        st.fexcept_arrays = [x, r]
        if not st.converged and raise_on_divergence:
            raise NotConvergedError(
                f"{niters} iterations, residual {st.rnrm2:.3e} > {res_tol:.3e}")
        return x
