"""Batched multi-RHS CG and block-CG: solve B systems for ~one's price.

Every solver tier before this one handled exactly one ``Ax=b`` per
process; a serving fleet answers MANY right-hand sides against a cached
operator (ROADMAP item 1).  Two rungs, sharing one batch layout -- RHS
are COLUMNS, every vector becomes ``(n, B)``:

* **Batched CG** (:func:`_batched_cg_program` /
  :func:`_batched_cg_pipelined_program`): the classic and
  Ghysels-Vanroose recurrences with a trailing batch axis.  ONE
  multi-vector SpMV per iteration amortizes the matrix HBM traffic
  B-fold (the planes/gather indices are read once for all columns),
  every per-RHS dot product collapses into a single B-wide column
  reduction, and per-RHS convergence masks ride the loop carry:
  a converged column FREEZES (``jnp.where`` on the mask -- its x/r/p
  never move again and its iteration counter stops) while the loop
  runs to the slowest unconverged RHS.  Per-column trajectories are
  exactly the single-RHS solver's (same update order, same
  convergence test), pinned by tests/test_batched.py.

* **Block CG** (:func:`_block_cg_program`): the true O'Leary block
  recurrence -- ONE shared Krylov block, B x B Gram matrices
  ``W = P^T A P`` / ``G = Z^T R`` solved per iteration, directions
  coupled across columns.  Converges in measurably fewer total
  iterations than B independent solves on ill-conditioned families
  (the ``--aniso`` acceptance): each block iteration expands the
  search space by up to B directions, implicitly deflating the
  extremal eigenvalues that dominate single-vector CG's count.
  Breakdown (a rank-deficient direction block -- converged columns
  deflate, near-parallel RHS collide) is handled by RANK DEFLATION:
  converged/dead columns are masked out of the search block and their
  Gram rows/columns replaced by identity, plus a relative Tikhonov
  jitter sized to the working precision so the B x B solves stay
  defined through exact rank collapse.

Disarmament contract: a batch of ONE delegates every program to the
plain :class:`~acg_tpu.solvers.jax_cg.JaxCGSolver` -- B=1 lowers
byte-identical HLO to the single-RHS tier (pinned in
tests/test_batched.py), and a CLI run without ``--nrhs`` never imports
this module on its solve path.
"""

from __future__ import annotations

import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from acg_tpu.errors import AcgError, ErrorCode, NotConvergedError
from acg_tpu.ops.precision import dot_compensated
from acg_tpu.ops.spmv import (BinnedEllMatrix, CooMatrix, DeviceMatrix,
                              DiaMatrix, EllMatrix, acc_dtype,
                              matrix_dtype, matrix_index_bytes,
                              spmv_flops)
from acg_tpu.solvers.stats import (SolverStats, StoppingCriteria,
                                   cg_flops_per_iteration)

__all__ = ["spmv_multi", "BatchedCGResult", "BatchedCGSolver"]


def spmv_multi(A: DeviceMatrix, X: jax.Array) -> jax.Array:
    """``Y = A @ X`` for a multi-column ``X`` of shape ``(n, B)``: one
    pass over the matrix amortized across all B columns -- the batched
    tier's throughput lever.  Every device format is supported; the
    DIA path stays gather-free (statically-sliced 2-D views)."""
    adt = acc_dtype(X.dtype)
    with jax.named_scope(f"spmv_multi_{type(A).__name__}"):
        if hasattr(A, "matfree_apply_multi"):
            # matrix-free operator tier (ops.operator): the batched
            # twin of the generated-plane apply -- the amortization is
            # total (there was no matrix traffic to amortize)
            return A.matfree_apply_multi(X)
        if hasattr(A, "matfree_apply"):
            # user operators registered without a multi-column form:
            # vmap the single-column apply over the batch axis
            return jax.vmap(lambda col: A.matfree_apply(col),
                            in_axes=1, out_axes=1)(X)
        if isinstance(A, DiaMatrix):
            L = max(0, -min(A.offsets))
            R = max(0, max(A.offsets) + A.nrows - X.shape[0])
            Xp = jnp.pad(X, ((L, R), (0, 0)))
            Y = jnp.zeros((A.nrows, X.shape[1]), dtype=adt)
            for plane, off in zip(A.data, A.offsets):
                sl = jax.lax.dynamic_slice_in_dim(Xp, L + off, A.nrows, 0)
                Y = Y + plane[:, None].astype(adt) * sl.astype(adt)
            return Y.astype(X.dtype)
        if isinstance(A, EllMatrix):
            return jnp.einsum("nk,nkb->nb", A.data, X[A.cols],
                              preferred_element_type=adt).astype(X.dtype)
        if isinstance(A, CooMatrix):
            prod = A.vals[:, None].astype(adt) * X[A.cols].astype(adt)
            return jax.ops.segment_sum(
                prod, A.rows, num_segments=A.nrows,
                indices_are_sorted=True).astype(X.dtype)
        if isinstance(A, BinnedEllMatrix):
            Y = jnp.zeros((A.nrows, X.shape[1]), dtype=adt)
            for rows, data, cols in zip(A.bin_rows, A.bin_data,
                                        A.bin_cols):
                contrib = jnp.einsum("mk,mkb->mb", data, X[cols],
                                     preferred_element_type=adt)
                Y = Y.at[rows].add(contrib, unique_indices=True)
            if A.tail_rows.size:
                prod = (A.tail_vals[:, None].astype(adt)
                        * X[A.tail_cols].astype(adt))
                Y = Y + jax.ops.segment_sum(
                    prod, A.tail_rows, num_segments=A.nrows,
                    indices_are_sorted=True)
            return Y.astype(X.dtype)
    raise TypeError(f"unsupported device matrix {type(A)}")


def _coldot_setup(dtype, precise: bool):
    """``(coldot, sdt)``: the per-column dot product (``(n,B),(n,B) ->
    (B,)`` -- ALL per-RHS dots in one fused reduction) and the scalar
    dtype, mirroring jax_cg._scalar_setup's storage policy."""
    sdt = acc_dtype(dtype)
    if precise:
        def one(u, v):
            hi, lo = dot_compensated(u.astype(sdt), v.astype(sdt))
            return hi + lo

        def coldot(a, c):
            return jax.vmap(one, in_axes=1)(a, c)
        return coldot, sdt

    def coldot(a, c):
        return jnp.einsum("nb,nb->b", a, c, preferred_element_type=sdt)
    return coldot, sdt


@functools.partial(jax.tree_util.register_dataclass,
                   data_fields=["x", "niterations", "k_total", "rnrm2",
                                "r0nrm2", "bnrm2", "x0nrm2", "converged"],
                   meta_fields=[])
@dataclasses.dataclass
class BatchedCGResult:
    """Device-resident batched solve result: every field except
    ``k_total`` (the loop trip count -- the slowest RHS's iteration
    number) carries a per-RHS column."""

    x: jax.Array            # (n, B)
    niterations: jax.Array  # (B,) int32: per-RHS frozen-at count
    k_total: jax.Array      # () int32: loop trip count (slowest RHS)
    rnrm2: jax.Array        # (B,)
    r0nrm2: jax.Array       # (B,)
    bnrm2: jax.Array        # (B,)
    x0nrm2: jax.Array       # (B,)
    converged: jax.Array    # (B,) bool


def _res_tols(res_atol, res_rtol, r0nrm2_cols):
    return jnp.maximum(res_atol, res_rtol * r0nrm2_cols)


def _col_where(mask, new, old):
    """Column-masked select: ``mask`` (B,), arrays ``(n, B)``."""
    return jnp.where(mask[None, :], new, old)


def _safe_div(num, den, active):
    """Masked per-column division: inactive columns get exactly 0 (a
    frozen column's update scale), and a 0 denominator on an active
    column -- progress exhausted at the precision floor -- freezes
    that column's step instead of poisoning it with inf."""
    ok = active & (den != 0)
    return jnp.where(ok, num / jnp.where(den != 0, den, 1.0),
                     jnp.zeros_like(num))


@functools.partial(jax.jit,
                   static_argnames=("unbounded", "precise", "precond",
                                    "trace", "state_io"))
def _batched_cg_program(A: DeviceMatrix, Bm, X0, res_atol, res_rtol,
                        maxits, unbounded: bool, precise: bool = False,
                        precond=None, mstate=None, trace: int = 0,
                        state_io: bool = False, carry=None):
    """Whole batched classic-CG solve as one XLA program.

    Per-column recurrences are the single-RHS classic program's; the
    B-wide column reductions fuse all per-RHS dots.  ``carry`` /
    ``state_io`` are the survivability tier's hooks (per-RHS leaves:
    r/p ``(n, B)``, gamma/done/iters ``(B,)``) -- the chunk driver
    threads them so a batch survives preemption mid-solve."""
    dtype = Bm.dtype
    coldot, sdt = _coldot_setup(dtype, precise)
    store = (lambda v: v.astype(dtype)) if sdt != dtype else (lambda v: v)
    nrhs = Bm.shape[1]
    bnrm2 = jnp.sqrt(coldot(Bm, Bm))
    x0nrm2 = jnp.sqrt(coldot(X0, X0))
    papply = None
    if precond is not None:
        from acg_tpu.precond import make_apply_batched
        papply = make_apply_batched(precond)
    if carry is not None:
        if precond is not None:
            R, P, gamma, rr, done0, iters0 = carry
            r0nrm2 = jnp.sqrt(rr)
        else:
            R, P, gamma = carry[:3]
            done0, iters0 = carry[3], carry[4]
            rr = gamma
            r0nrm2 = jnp.sqrt(gamma)
    elif precond is not None:
        R = Bm - spmv_multi(A, X0)
        Z0 = papply(mstate, A, R)
        P = store(Z0)
        gamma = coldot(R, Z0)
        rr = coldot(R, R)
        r0nrm2 = jnp.sqrt(rr)
    else:
        R = Bm - spmv_multi(A, X0)
        P = R
        gamma = rr = coldot(R, R)
        r0nrm2 = jnp.sqrt(gamma)
    res_tol = _res_tols(res_atol, res_rtol, r0nrm2)
    if trace:
        from acg_tpu import telemetry

    def body(k, st):
        if trace:
            buf, st = st[-1], st[:-1]
        X, R, P, gamma, done, iters = st[:6]
        rr_c = st[6] if precond is not None else None
        active = ~done
        T = spmv_multi(A, P)
        pdott = coldot(P, T)
        alpha = _safe_div(gamma, pdott, active)
        X = _col_where(active, store(X + alpha[None, :] * P), X)
        R = _col_where(active, store(R - alpha[None, :] * T), R)
        if precond is not None:
            Z = papply(mstate, A, R)
            gamma_next = coldot(R, Z)
            rr_next = coldot(R, R)
            conv_sqr = rr_next
        else:
            gamma_next = conv_sqr = coldot(R, R)
        beta = _safe_div(gamma_next, gamma, active)
        nextP = store(((Z if precond is not None else R)
                       + beta[None, :] * P))
        P = _col_where(active, nextP, P)
        iters = iters + active.astype(jnp.int32)
        gamma = jnp.where(active, gamma_next, gamma)
        if not unbounded:
            done = done | (active & (conv_sqr < res_tol * res_tol))
        out = (X, R, P, gamma, done, iters)
        if precond is not None:
            out = out + (jnp.where(active, rr_next, rr_c),)
        if trace:
            out = out + (telemetry.ring_record_batched(
                buf, k, conv_sqr),)
        return out

    if carry is not None:
        done0 = done0.astype(bool)
        iters0 = iters0.astype(jnp.int32)
    else:
        iters0 = jnp.zeros((nrhs,), jnp.int32)
        done0 = (jnp.zeros((nrhs,), bool) if unbounded
                 else rr < res_tol * res_tol)
    init = (X0, R, P, gamma, done0, iters0)
    if precond is not None:
        init = init + (rr,)
    if trace:
        init = init + (telemetry.ring_init_batched(trace, nrhs, sdt),)

    if unbounded:
        state = jax.lax.fori_loop(0, maxits, body, init)
        k = maxits
    else:
        def cond(c):
            k, st = c
            return (k < maxits) & jnp.any(~st[4])

        def wbody(c):
            k, st = c
            return (k + 1, body(k, st))

        k, state = jax.lax.while_loop(cond, wbody, (jnp.int32(0), init))
    X, R, P, gamma, done, iters = state[:6]
    rr_fin = state[6] if precond is not None else gamma
    # "converged" = ran the budget on the unbounded path -- but ONLY
    # in the reported result: the state_io carry keeps the loop's own
    # mask and iteration totals, or a later chunk would see every
    # column frozen and silently do nothing
    done_res = jnp.ones((nrhs,), bool) if unbounded else done
    res = BatchedCGResult(
        x=X, niterations=iters, k_total=jnp.asarray(k, jnp.int32),
        rnrm2=jnp.sqrt(rr_fin), r0nrm2=r0nrm2, bnrm2=bnrm2,
        x0nrm2=x0nrm2, converged=done_res)
    extras = ()
    if trace:
        extras = extras + (state[-1],)
    if state_io:
        core = (R, P, gamma)
        if precond is not None:
            core = core + (rr_fin,)
        core = core + (done, iters)
        extras = extras + (core,)
    return (res,) + extras if extras else res


@functools.partial(jax.jit,
                   static_argnames=("unbounded", "precise", "precond",
                                    "trace"))
def _batched_cg_pipelined_program(A: DeviceMatrix, Bm, X0, res_atol,
                                  res_rtol, maxits, unbounded: bool,
                                  precise: bool = False, precond=None,
                                  mstate=None, trace: int = 0):
    """Whole batched Ghysels-Vanroose solve as one XLA program: the
    pipelined recurrences with a trailing batch axis.  BOTH per-RHS
    reduction families (gamma and delta, 2B scalars) are computed at
    one program point, so the distributed twin fuses them into a
    SINGLE allreduce whose payload grows with B while the collective
    COUNT stays 1 (acg_tpu.parallel.dist_batched)."""
    dtype = Bm.dtype
    coldot, sdt = _coldot_setup(dtype, precise)
    store = (lambda v: v.astype(dtype)) if sdt != dtype else (lambda v: v)
    nrhs = Bm.shape[1]
    bnrm2 = jnp.sqrt(coldot(Bm, Bm))
    x0nrm2 = jnp.sqrt(coldot(X0, X0))
    papply = None
    if precond is not None:
        from acg_tpu.precond import make_apply_batched
        papply = make_apply_batched(precond)
        R = Bm - spmv_multi(A, X0)
        U0 = store(papply(mstate, A, R))
        W = spmv_multi(A, U0)
        rr0 = coldot(R, R)
        r0nrm2 = jnp.sqrt(rr0)
    else:
        R = Bm - spmv_multi(A, X0)
        W = spmv_multi(A, R)
        rr0 = coldot(R, R)
        r0nrm2 = jnp.sqrt(rr0)
    res_tol = _res_tols(res_atol, res_rtol, r0nrm2)
    inf = jnp.full((nrhs,), jnp.inf, sdt)
    zeros = jnp.zeros_like(Bm)
    if trace:
        from acg_tpu import telemetry

    def pbody(k, st):
        """Preconditioned GV, batched: carry mirrors jax_cg's pbody
        with per-RHS scalar vectors."""
        if trace:
            buf, st = st[-1], st[:-1]
        (X, R, U, W, P, S, Q, Z, gamma_prev, alpha_prev, rr, done,
         iters) = st
        active = ~done
        gamma = coldot(R, U)
        delta = coldot(W, U)
        rr_new = coldot(R, R)
        M_ = papply(mstate, A, W)
        Nv = spmv_multi(A, M_)
        beta = _safe_div(gamma, gamma_prev, active)
        denom = delta - beta * _safe_div(gamma, alpha_prev, active)
        alpha = _safe_div(gamma, denom, active)
        Z = _col_where(active, store(Nv + beta[None, :] * Z), Z)
        Q = _col_where(active, store(M_ + beta[None, :] * Q), Q)
        S = _col_where(active, store(W + beta[None, :] * S), S)
        P = _col_where(active, store(U + beta[None, :] * P), P)
        X = _col_where(active, store(X + alpha[None, :] * P), X)
        R = _col_where(active, store(R - alpha[None, :] * S), R)
        U = _col_where(active, store(U - alpha[None, :] * Q), U)
        W = _col_where(active, store(W - alpha[None, :] * Z), W)
        iters = iters + active.astype(jnp.int32)
        if not unbounded:
            # the stale test of the pipelined tier: rr_new is this
            # body's pre-update ||r||^2 (jax_cg convergence semantics)
            done = done | (active & (rr_new < res_tol * res_tol))
        gamma_c = jnp.where(active, gamma, gamma_prev)
        alpha_c = jnp.where(active, alpha, alpha_prev)
        rr_c = jnp.where(active, rr_new, rr)
        out = (X, R, U, W, P, S, Q, Z, gamma_c, alpha_c, rr_c, done,
               iters)
        if trace:
            out = out + (telemetry.ring_record_batched(buf, k, rr_new),)
        return out

    def body(k, st):
        if trace:
            buf, st = st[-1], st[:-1]
        X, R, W, P, T, Z, gamma_prev, alpha_prev, done, iters = st
        active = ~done
        # BOTH reduction families at one point: the fused 2B-scalar
        # allreduce of the distributed twin
        gamma = coldot(R, R)
        delta = coldot(W, R)
        Q = spmv_multi(A, W)
        beta = _safe_div(gamma, gamma_prev, active)
        denom = delta - beta * _safe_div(gamma, alpha_prev, active)
        alpha = _safe_div(gamma, denom, active)
        Z = _col_where(active, store(Q + beta[None, :] * Z), Z)
        T = _col_where(active, store(W + beta[None, :] * T), T)
        P = _col_where(active, store(R + beta[None, :] * P), P)
        X = _col_where(active, store(X + alpha[None, :] * P), X)
        R = _col_where(active, store(R - alpha[None, :] * T), R)
        W = _col_where(active, store(W - alpha[None, :] * Z), W)
        iters = iters + active.astype(jnp.int32)
        if not unbounded:
            done = done | (active & (gamma < res_tol * res_tol))
        gamma_c = jnp.where(active, gamma, gamma_prev)
        alpha_c = jnp.where(active, alpha, alpha_prev)
        out = (X, R, W, P, T, Z, gamma_c, alpha_c, done, iters)
        if trace:
            out = out + (telemetry.ring_record_batched(buf, k, gamma),)
        return out

    iters0 = jnp.zeros((nrhs,), jnp.int32)
    done0 = (jnp.zeros((nrhs,), bool) if unbounded
             else rr0 < res_tol * res_tol)
    if precond is not None:
        init = (X0, R, U0, W, zeros, zeros, zeros, zeros, inf, inf,
                rr0, done0, iters0)
        loop = pbody
    else:
        init = (X0, R, W, zeros, zeros, zeros, inf, inf, done0, iters0)
        loop = body
    if trace:
        init = init + (telemetry.ring_init_batched(trace, nrhs, sdt),)
    if unbounded:
        state = jax.lax.fori_loop(0, maxits, loop, init)
        k = maxits
    else:
        def cond(c):
            k, st = c
            done = st[11] if precond is not None else st[8]
            return (k < maxits) & jnp.any(~done)

        def wbody(c):
            k, st = c
            return (k + 1, loop(k, st))

        k, state = jax.lax.while_loop(cond, wbody, (jnp.int32(0), init))
    if trace:
        tbuf, state = state[-1], state[:-1]
    X, R = state[0], state[1]
    done = state[11] if precond is not None else state[8]
    iters = state[12] if precond is not None else state[9]
    if unbounded:
        done = jnp.ones((nrhs,), bool)
    rnrm2 = jnp.sqrt(coldot(R, R))
    # stale-test consistency (jax_cg rationale): a fresh final residual
    # at tolerance counts as converged even if the in-loop stale test
    # never fired before maxits
    done = done | (rnrm2 <= res_tol)
    res = BatchedCGResult(
        x=X, niterations=iters, k_total=jnp.asarray(k, jnp.int32),
        rnrm2=rnrm2, r0nrm2=r0nrm2, bnrm2=bnrm2, x0nrm2=x0nrm2,
        converged=done)
    return (res, tbuf) if trace else res


@functools.partial(jax.jit,
                   static_argnames=("unbounded", "precond", "trace"))
def _block_cg_program(A: DeviceMatrix, Bm, X0, res_atol, res_rtol,
                      maxits, unbounded: bool, precond=None,
                      mstate=None, trace: int = 0):
    """Whole block-CG solve (O'Leary 1980) as one XLA program.

    One shared Krylov block: per iteration ONE multi-vector SpMV, two
    B x B Gram systems (``W alpha = G`` for the step, ``G beta =
    G_new`` for the direction update).  Unlike the batched mode, a
    converged column KEEPS RIDING the shared block (the coupling is
    what buys the iteration-count win); its crossing iteration is
    recorded in the per-RHS counter and further updates only refine
    it.  Rank deflation on breakdown: a rank-deficient Gram matrix
    (parallel RHS, a direction exhausted, the whole block converged)
    is deflated by a relative Tikhonov jitter sized to the scalar
    precision -- the null directions contribute ~nothing to the step
    instead of producing NaNs.  All B x B arithmetic runs in the
    scalar dtype ``sdt``."""
    dtype = Bm.dtype
    coldot, sdt = _coldot_setup(dtype, False)
    store = (lambda v: v.astype(dtype)) if sdt != dtype else (lambda v: v)
    nrhs = Bm.shape[1]
    eps = jnp.asarray(jnp.finfo(sdt).eps, sdt)
    bnrm2 = jnp.sqrt(coldot(Bm, Bm))
    x0nrm2 = jnp.sqrt(coldot(X0, X0))
    papply = None
    if precond is not None:
        from acg_tpu.precond import make_apply_batched
        papply = make_apply_batched(precond)

    def gram(Aa, Bb):
        return jnp.einsum("ni,nj->ij", Aa.astype(sdt), Bb.astype(sdt),
                          preferred_element_type=sdt)

    def deflated_solve(M, G):
        """Solve ``M a = G`` through a relative Tikhonov jitter: a
        rank-deficient M (breakdown: parallel RHS, exhausted
        directions, a fully-converged block) deflates its null
        directions to ~zero step instead of NaNs."""
        tr = jnp.trace(M) / M.shape[0]
        jitter = 64.0 * eps * jnp.maximum(jnp.abs(tr), eps)
        return jnp.linalg.solve(M + jitter * jnp.eye(M.shape[0],
                                                     dtype=sdt), G)

    R = (Bm - spmv_multi(A, X0)).astype(sdt)
    rr0 = coldot(R, R)
    r0nrm2 = jnp.sqrt(rr0)
    res_tol = _res_tols(res_atol, res_rtol, r0nrm2)
    done0 = (jnp.zeros((nrhs,), bool) if unbounded
             else rr0 < res_tol * res_tol)
    Z = papply(mstate, A, R).astype(sdt) if precond is not None else R
    P = Z
    G0 = gram(Z, R)
    if trace:
        from acg_tpu import telemetry

    def body(k, st):
        if trace:
            buf, st = st[-1], st[:-1]
        X, R, P, G, done, iters = st
        active = ~done
        Q = spmv_multi(A, store(P)).astype(sdt)
        W = gram(P, Q)
        alpha = deflated_solve(W, G)
        X = X + P @ alpha
        R = R - Q @ alpha
        rr = coldot(R, R)
        iters = iters + active.astype(jnp.int32)
        if not unbounded:
            done = done | (active & (rr < res_tol * res_tol))
        Zn = (papply(mstate, A, store(R)).astype(sdt)
              if precond is not None else R)
        G_new = gram(Zn, R)
        beta = deflated_solve(G, G_new)
        P = Zn + P @ beta
        out = (X, R, P, G_new, done, iters)
        if trace:
            out = out + (telemetry.ring_record_batched(buf, k, rr),)
        return out

    init = (X0.astype(sdt), R, P, G0, done0,
            jnp.zeros((nrhs,), jnp.int32))
    if trace:
        init = init + (telemetry.ring_init_batched(trace, nrhs, sdt),)
    if unbounded:
        state = jax.lax.fori_loop(0, maxits, body, init)
        k = maxits
    else:
        def cond(c):
            k, st = c
            return (k < maxits) & jnp.any(~st[4])

        def wbody(c):
            k, st = c
            return (k + 1, body(k, st))

        k, state = jax.lax.while_loop(cond, wbody, (jnp.int32(0), init))
    if trace:
        tbuf, state = state[-1], state[:-1]
    X, R, P, G, done, iters = state
    rr_fin = coldot(R, R)
    if unbounded:
        done = jnp.ones((nrhs,), bool)
    res = BatchedCGResult(
        x=store(X), niterations=iters, k_total=jnp.asarray(k, jnp.int32),
        rnrm2=jnp.sqrt(rr_fin), r0nrm2=r0nrm2, bnrm2=bnrm2,
        x0nrm2=x0nrm2, converged=done)
    return (res, tbuf) if trace else res


class BatchedCGSolver:
    """Multi-RHS CG over one :class:`DeviceMatrix`: B systems sharing
    the operator, solved by the batched (default), batched-pipelined
    or block recurrence.

    ``mode``: ``"batched"`` (vmapped classic), ``"pipelined"``
    (vmapped Ghysels-Vanroose) or ``"block"`` (true block CG).
    ``precond`` broadcasts over the batch axis
    (:func:`acg_tpu.precond.make_apply_batched`).  ``trace`` arms the
    per-RHS residual ring (telemetry.BatchedConvergenceTrace);
    ``ckpt`` (an acg_tpu.checkpoint.CheckpointConfig) arms the
    host-chunked snapshot driver for the batched-classic mode -- the
    carry's per-RHS leaves (r/p columns, gamma/done/iters vectors)
    survive preemption and resume exactly.

    A single-column ``b`` (B=1) delegates solve AND lower_solve to a
    plain :class:`JaxCGSolver` with the same configuration -- the
    lowered program is byte-identical to the single-RHS tier's (the
    disarmed-identity discipline, pinned in tests/test_batched.py)."""

    _ckpt_tier = "jax-cg-batched"

    def __init__(self, A: DeviceMatrix, mode: str = "batched",
                 precise_dots: bool = False, kernels: str = "auto",
                 vector_dtype=None, precond=None, trace: int = 0,
                 ckpt=None, host_matrix=None):
        if mode not in ("batched", "pipelined", "block"):
            raise ValueError(f"unknown batched mode {mode!r} "
                             f"(batched, pipelined, block)")
        if kernels not in ("auto", "xla"):
            raise ValueError(
                "the batched tiers run the XLA multi-vector SpMV "
                "(one matrix pass over all B columns); kernels="
                f"{kernels!r} is single-RHS only -- use 'auto'/'xla'")
        if mode == "block" and precise_dots:
            raise ValueError("block-CG's scalars are B x B Gram solves "
                             "in the scalar dtype; precise_dots applies "
                             "to the batched/pipelined modes")
        self.A = A
        self.mode = mode
        self.precise_dots = bool(precise_dots)
        self.vector_dtype = vector_dtype
        from acg_tpu.precond import parse_precond
        self.precond_spec = parse_precond(precond)
        self._mstate = None
        self.trace = int(trace)
        if self.trace < 0:
            raise ValueError("trace must be >= 0")
        if ckpt is not None:
            from acg_tpu.checkpoint import CheckpointConfig
            if not isinstance(ckpt, CheckpointConfig):
                raise ValueError("ckpt must be an acg_tpu.checkpoint."
                                 "CheckpointConfig or None")
            if mode != "batched":
                raise ValueError(
                    "batched checkpointing threads the batched-classic "
                    "carry (r/p columns + gamma/done/iters); the "
                    "pipelined/block modes do not expose state_io -- "
                    "use mode='batched'")
        self.ckpt = ckpt
        self.host_matrix = host_matrix
        self.last_trace = None
        self.stats = SolverStats(unknowns=A.nrows)
        # the B=1 delegate: constructed lazily, shares this tier's
        # configuration so delegation is byte-identical to a plain
        # single-RHS build
        self._inner1 = None
        self._spmv_flops_cache = None

    # -- shared plumbing --------------------------------------------------

    def _solve_dtype(self):
        dtype = matrix_dtype(self.A)
        if self.vector_dtype is not None:
            dtype = jnp.dtype(self.vector_dtype)
        return dtype

    def _inner(self):
        if self._inner1 is None:
            from acg_tpu.solvers.jax_cg import JaxCGSolver
            self._inner1 = JaxCGSolver(
                self.A, pipelined=(self.mode == "pipelined"),
                precise_dots=self.precise_dots, kernels="xla",
                vector_dtype=self.vector_dtype,
                precond=self.precond_spec, trace=self.trace,
                host_matrix=self.host_matrix,
                ckpt=self.ckpt)
        return self._inner1

    def _ensure_precond_state(self):
        if self.precond_spec is None or self._mstate is not None:
            return self._mstate
        from acg_tpu.ops.spmv import spmv
        from acg_tpu.precond import setup_single
        sdt = acc_dtype(self._solve_dtype())
        self._mstate = setup_single(self.precond_spec, self.A,
                                    spmv, sdt)
        return self._mstate

    def _as_columns(self, v, dtype):
        v = jnp.asarray(v, dtype=dtype)
        if v.ndim == 1:
            v = v[:, None]
        if v.ndim != 2 or v.shape[0] != self.A.nrows:
            raise ValueError(
                f"batched right-hand sides are (n, B) columns; got "
                f"shape {tuple(v.shape)} for n={self.A.nrows}")
        return v

    def _check_criteria(self, crit: StoppingCriteria):
        if crit.needs_diff:
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                "the batched tiers support residual criteria only "
                "(a per-RHS diff criterion is not part of the batched "
                "carry)")

    def _select_program(self, Bm, X0, crit: StoppingCriteria,
                        state_io: bool = False, carry=None):
        sdt = acc_dtype(Bm.dtype)
        args = (self.A, Bm, X0,
                jnp.asarray(crit.residual_atol, sdt),
                jnp.asarray(crit.residual_rtol, sdt),
                jnp.int32(crit.maxits))
        kwargs = dict(unbounded=crit.unbounded, trace=self.trace)
        if self.mode == "block":
            program = _block_cg_program
        elif self.mode == "pipelined":
            program = _batched_cg_pipelined_program
            kwargs["precise"] = self.precise_dots
        else:
            program = _batched_cg_program
            kwargs["precise"] = self.precise_dots
            if state_io:
                kwargs["state_io"] = True
            if carry is not None:
                kwargs["carry"] = carry
        if self.precond_spec is not None:
            kwargs["precond"] = self.precond_spec
            kwargs["mstate"] = self._ensure_precond_state()
        return program, args, kwargs

    def lower_solve(self, b, x0=None, criteria=None):
        """Lower (don't run) the exact program this configuration
        dispatches -- the HLO-pin hook.  B=1 delegates to the plain
        single-RHS solver, so the lowered text is byte-identical to
        the unbatched tier's."""
        crit = criteria or StoppingCriteria()
        dtype = self._solve_dtype()
        Bm = self._as_columns(b, dtype)
        if Bm.shape[1] == 1:
            return self._inner().lower_solve(
                Bm[:, 0], x0=None if x0 is None
                else self._as_columns(x0, dtype)[:, 0],
                criteria=criteria)
        self._check_criteria(crit)
        X0 = (jnp.zeros_like(Bm) if x0 is None
              else self._as_columns(x0, dtype))
        program, args, kwargs = self._select_program(Bm, X0, crit)
        return program.lower(*args, **kwargs)

    # -- solve ------------------------------------------------------------

    def solve(self, b, x0=None, criteria: StoppingCriteria | None = None,
              raise_on_divergence: bool = True, warmup: int = 0,
              host_result: bool = True):
        """Solve ``A X = B`` for the (n, B) column block ``b``.
        Returns the (n, B) solution block (host numpy unless
        ``host_result=False``); per-RHS evidence lands in
        ``stats.batch``."""
        crit = criteria or StoppingCriteria()
        dtype = self._solve_dtype()
        from acg_tpu import telemetry
        st = self.stats
        st.criteria = crit
        t_xfer = time.perf_counter()
        with telemetry.annotate("transfer"):
            Bm = self._as_columns(b, dtype)
            X0 = (jnp.zeros_like(Bm) if x0 is None
                  else self._as_columns(x0, dtype))
        telemetry.add_timing(st, "transfer",
                             time.perf_counter() - t_xfer)
        nrhs = int(Bm.shape[1])
        if nrhs == 1:
            # the disarmed-identity path: ONE column runs the plain
            # single-RHS program byte-for-byte
            inner = self._inner()
            x = inner.solve(np.asarray(Bm[:, 0]) if host_result
                            else Bm[:, 0],
                            x0=None if x0 is None else np.asarray(X0[:, 0]),
                            criteria=crit,
                            raise_on_divergence=raise_on_divergence,
                            warmup=warmup, host_result=host_result)
            self.stats = st = inner.stats
            self.last_trace = inner.last_trace
            st.batch = {"nrhs": 1, "mode": self.mode,
                        "iterations": [int(st.niterations)],
                        "rnrm2": [float(st.rnrm2)],
                        "converged": [bool(st.converged)],
                        "iterations_max": int(st.niterations),
                        "iterations_sum": int(st.niterations)}
            if host_result:
                return np.asarray(x).reshape(-1, 1)
            return x[:, None] if x.ndim == 1 else x
        self._check_criteria(crit)
        if self.ckpt is not None:
            return self._solve_ckpt(Bm, X0, crit, raise_on_divergence,
                                    warmup, host_result)
        program, args, kwargs = self._select_program(Bm, X0, crit)

        def run():
            out = program(*args, **kwargs)
            if self.trace:
                return out[0], out[1]
            return out, None

        from acg_tpu._platform import block_until_ready_works, device_sync
        block_until_ready_works()
        t_warm = time.perf_counter()
        with telemetry.annotate("compile"):
            for _ in range(max(warmup, 0)):
                device_sync(run()[0].x)
        if warmup > 0:
            telemetry.add_timing(st, "compile",
                                 time.perf_counter() - t_warm)
        t0 = time.perf_counter()
        with telemetry.annotate("solve"):
            res, tbuf = run()
            device_sync(res.x)
        t_solve = time.perf_counter() - t0
        st.tsolve += t_solve
        telemetry.add_timing(st, "solve", t_solve)
        self._finish_stats(res, t_solve, nrhs, tbuf)
        x = np.asarray(res.x) if host_result else res.x
        if host_result:
            st.fexcept_arrays = [x]
        else:
            has_nan = bool(jnp.isnan(res.x).any())
            has_inf = bool(jnp.isinf(res.x).any())
            st.fexcept_arrays = [np.asarray([np.nan if has_nan else 0.0,
                                             np.inf if has_inf else 0.0])]
        if not st.converged and raise_on_divergence:
            worst = int(np.argmax(np.asarray(res.rnrm2)))
            raise NotConvergedError(
                f"{st.niterations} iterations, {st.batch['unconverged']}"
                f" of {nrhs} RHS unconverged (worst rhs {worst}, "
                f"residual {float(np.asarray(res.rnrm2)[worst]):.3e})")
        return x

    def _finish_stats(self, res: BatchedCGResult, t_solve: float,
                      nrhs: int, tbuf=None, executed=None) -> None:
        """Per-RHS evidence -> stats.batch + the service hooks; the
        aggregate fields keep their single-RHS meaning via the
        slowest/worst RHS."""
        from acg_tpu import metrics, observatory, telemetry
        st = self.stats
        iters = np.asarray(res.niterations).astype(int).tolist()
        rn = [float(v) for v in np.asarray(res.rnrm2)]
        conv = [bool(v) for v in np.asarray(res.converged)]
        k_total = int(res.k_total) if executed is None else int(executed)
        st.nsolves += 1
        st.niterations = k_total
        st.ntotaliterations += k_total
        st.bnrm2 = float(np.max(np.asarray(res.bnrm2)))
        st.x0nrm2 = float(np.max(np.asarray(res.x0nrm2)))
        st.r0nrm2 = float(np.max(np.asarray(res.r0nrm2)))
        st.rnrm2 = float(max(rn))
        st.dxnrm2 = float("inf")
        st.converged = all(conv)
        st.batch = {
            "nrhs": nrhs,
            "mode": self.mode,
            "iterations": iters,
            "iterations_max": int(max(iters) if iters else 0),
            "iterations_sum": int(sum(iters)),
            "rnrm2": rn,
            "converged": conv,
            "unconverged": int(sum(1 for c in conv if not c)),
        }
        if self.mode == "block":
            # the work metric of the acceptance criterion: each block
            # iteration advances all B columns, so the comparable
            # "total iterations" figure is trips x B
            st.batch["block_iterations"] = k_total
            st.batch["total_iterations"] = k_total * nrhs
        if tbuf is not None:
            st.trace = self.last_trace = \
                telemetry.BatchedConvergenceTrace.from_ring(
                    np.asarray(tbuf), k_total,
                    solver=f"cg-{self.mode}")
        metrics.record_solve(t_solve, k_total, st.converged,
                             solver=f"cg-{self.mode}"
                             if self.mode != "batched" else "cg-batched")
        observatory.note_batch(nrhs, rn, conv)
        self._account_ops(st, k_total, nrhs)

    def _account_ops(self, st, k_total: int, nrhs: int) -> None:
        """Analytic census: matrix bytes are read ONCE per iteration
        for the whole batch (the amortization this tier exists for);
        vector traffic and flops scale with B."""
        if self._spmv_flops_cache is None:
            self._spmv_flops_cache = spmv_flops(self.A)
        n = self.A.nrows
        nnz3 = self._spmv_flops_cache / 3.0
        per_it = cg_flops_per_iteration(nnz3, n,
                                        self.mode == "pipelined")
        # flops scale with B (every column multiplies every nonzero);
        # only the matrix BYTES amortize -- that asymmetry is the tier
        st.nflops += (per_it * k_total + self._spmv_flops_cache
                      + 2.0 * n) * nrhs
        dtype = self._solve_dtype()
        dbl = np.dtype(dtype).itemsize
        mat_dbl = np.dtype(matrix_dtype(self.A)).itemsize
        idx_b = matrix_index_bytes(self.A)
        mat_bytes = int(nnz3 * (mat_dbl + idx_b))
        st.ops["gemv"].add(k_total + 1, 0.0,
                           (mat_bytes + 2 * n * dbl * nrhs)
                           * (k_total + 1))
        st.ops["dot"].add(k_total, 0.0, 2 * n * dbl * nrhs * k_total)
        st.ops["nrm2"].add(k_total + 1, 0.0,
                           n * dbl * nrhs * (k_total + 1))
        st.ops["axpy"].add(3 * k_total, 0.0,
                           3 * n * dbl * nrhs * 3 * k_total)

    # -- survivability: chunked batched solve ------------------------------

    def _solve_ckpt(self, Bm, X0, crit, raise_on_divergence: bool,
                    warmup: int, host_result: bool):
        """Checkpoint-armed batched solve: the UNCHANGED batched
        classic program dispatched in chunks with the per-RHS carry
        (r/p columns + gamma/done/iters vectors) threaded through and
        snapshotted -- a batch survives preemption with every RHS's
        progress intact, and resumes to the original per-RHS
        tolerances."""
        from acg_tpu import checkpoint as ckpt_mod
        from acg_tpu import metrics, observatory, telemetry
        from acg_tpu._platform import block_until_ready_works, device_sync
        cfg = self.ckpt
        st = self.stats
        st.criteria = crit
        nrhs = int(Bm.shape[1])
        dtype = self._solve_dtype()
        sdt = acc_dtype(dtype)
        b_crc = ckpt_mod.vector_checksum(np.asarray(Bm))
        names = ckpt_mod.batched_carry_names(
            self.precond_spec is not None)

        def chunk_args(x_dev, atol_cols, rtol, m):
            return (self.A, Bm, x_dev,
                    jnp.asarray(atol_cols, sdt),
                    jnp.asarray(rtol, sdt), jnp.int32(m))

        consumed = 0
        executed = 0
        resumed_from = None
        carry = None
        x_cur = X0
        abs_tol = None
        first_r0 = None
        snap = cfg.resume
        if snap is not None:
            ckpt_mod.validate_resume(
                snap, tier=self._ckpt_tier, pipelined=False,
                precond=(str(self.precond_spec)
                         if self.precond_spec is not None else None),
                n=int(self.A.nrows), dtype=dtype, b_crc=b_crc,
                nrhs=nrhs)
            consumed = resumed_from = snap.iteration
            sm = snap.meta
            abs_tol = np.asarray(sm["abs_tol"], dtype=np.float64)
            first_r0 = np.asarray(sm["r0nrm2"], dtype=np.float64)
            x_cur = jnp.asarray(snap.arrays["x"], dtype=dtype)
            carry = tuple(jnp.asarray(snap.arrays[nm])
                          for nm in names[1:])
            metrics.record_resume()
            telemetry.record_event(
                st, "resume",
                f"resumed batched solve ({nrhs} RHS) from snapshot at "
                f"iteration {consumed}")
        block_until_ready_works()

        def run(a, carry):
            out = _batched_cg_program(
                *a, unbounded=crit.unbounded,
                precise=self.precise_dots, trace=self.trace,
                state_io=True, carry=carry,
                **({"precond": self.precond_spec,
                    "mstate": self._ensure_precond_state()}
                   if self.precond_spec is not None else {}))
            ring = out[1] if self.trace else None
            return out[0], ring, out[-1]

        seq = 0
        nsnaps = 0
        ck_secs = 0.0
        res = None
        t0 = time.perf_counter()
        with telemetry.annotate("solve"):
            while True:
                remaining = crit.maxits - consumed
                if remaining <= 0:
                    break
                m = min(cfg.chunk_for(None), remaining)
                if abs_tol is None:
                    a = chunk_args(x_cur,
                                   jnp.full((nrhs,), crit.residual_atol),
                                   crit.residual_rtol, m)
                else:
                    a = chunk_args(x_cur, abs_tol, 0.0, m)
                res, tbuf, core = run(a, carry)
                device_sync(res.x)
                k_chunk = int(res.k_total)
                consumed += k_chunk
                executed += k_chunk
                if first_r0 is None:
                    first_r0 = np.asarray(res.r0nrm2, dtype=np.float64)
                    abs_tol = np.maximum(crit.residual_atol,
                                         crit.residual_rtol * first_r0)
                if self.trace and tbuf is not None:
                    st.trace = self.last_trace = \
                        telemetry.BatchedConvergenceTrace.from_ring(
                            np.asarray(tbuf), k_chunk,
                            solver="cg-batched",
                            offset=consumed - k_chunk)
                # status plane: the ETA keys to the SLOWEST unconverged
                # RHS -- its residual is the one the endpoint samples
                rn = np.asarray(res.rnrm2)
                conv = np.asarray(res.converged)
                worst = (float(np.max(rn[~conv])) if (~conv).any()
                         else float(np.max(rn)))
                observatory.note_chunk(
                    self._ckpt_tier, consumed, worst,
                    abs_tol=float(np.max(abs_tol)),
                    rtol=crit.residual_rtol)
                observatory.note_batch(
                    nrhs, [float(v) for v in rn],
                    [bool(v) for v in conv])
                finished = (consumed >= crit.maxits if crit.unbounded
                            else bool(conv.all()))
                x_cur = res.x
                carry = core
                if cfg.path is not None and not finished:
                    t_ck = time.perf_counter()
                    arrs = {"x": np.asarray(res.x)}
                    for nm, leaf in zip(names[1:], core):
                        arrs[nm] = np.asarray(leaf)
                    seq += 1
                    meta = {
                        "tier": self._ckpt_tier,
                        "pipelined": False,
                        "precond": (str(self.precond_spec)
                                    if self.precond_spec is not None
                                    else None),
                        "n": int(self.A.nrows),
                        "nrhs": nrhs,
                        "dtype": str(np.dtype(dtype)),
                        "iteration": consumed,
                        "seq": seq,
                        "abs_tol": [float(v) for v in abs_tol],
                        "bnrm2": [float(v)
                                  for v in np.asarray(res.bnrm2)],
                        "x0nrm2": [float(v)
                                   for v in np.asarray(res.x0nrm2)],
                        "r0nrm2": [float(v) for v in first_r0],
                        "b_crc": b_crc,
                        "trace_tail": [],
                    }
                    nbytes = ckpt_mod.save_snapshot(cfg.path, meta,
                                                    arrs)
                    dt = time.perf_counter() - t_ck
                    ck_secs += dt
                    telemetry.add_timing(st, "ckpt", dt)
                    metrics.record_snapshot(nbytes, dt)
                    nsnaps += 1
                if finished:
                    break
        if res is None:
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                f"snapshot iteration {consumed} already meets the "
                f"iteration cap {crit.maxits}; raise --max-iterations "
                f"to continue this solve")
        t_solve = time.perf_counter() - t0 - ck_secs
        st.tsolve += t_solve
        telemetry.add_timing(st, "solve", t_solve)
        self._finish_stats(res, t_solve, nrhs, None, executed=executed)
        st.ckpt = {
            "path": cfg.path,
            "every": int(cfg.every),
            "snapshots": nsnaps,
            "iteration": consumed,
            "rollbacks": 0,
        }
        if resumed_from is not None:
            st.ckpt["resumed_from"] = resumed_from
        x = np.asarray(res.x) if host_result else res.x
        if host_result:
            st.fexcept_arrays = [x]
        if not st.converged and raise_on_divergence:
            raise NotConvergedError(
                f"{executed} iterations, "
                f"{st.batch['unconverged']} of {nrhs} RHS unconverged")
        return x
