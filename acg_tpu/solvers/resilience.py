"""Breakdown-recovery policy for the CG solvers.

Pipelined and reduced-precision CG are numerically brittle: deep
pipelining and rounded recurrences can drive the residual non-finite or
(p, Ap) non-positive mid-solve (Cornelis & Vanroose, arXiv:1801.04728;
Cools et al., arXiv:1905.06850), and on a mesh a flaky transport can
inject the same poison from outside the arithmetic.  The standard
hardening move is detected-breakdown restart: the jitted loops flag the
breakdown in solver state (``detect=True`` programs in
:mod:`acg_tpu.solvers.jax_cg` / :mod:`acg_tpu.parallel.dist`), exit
early, and a HOST-side policy -- this module -- decides what happens
next:

  1. bounded restarts with backoff: re-enter the solve from the last
     finite iterate; the program's setup recomputes the TRUE residual
     ``r = b - A x0``, so the restart discards the poisoned recurrence
     state the same way the bf16 tier's replacement segments do;
  2. transport fallback (distributed): a second breakdown under
     ``comm="dma"`` retires the one-sided transport for the solve and
     rebuilds the program on the ``"xla"`` collectives;
  3. final fallback to the host reference solver when a matrix is
     available there;
  4. multi-controller: every restart/abort decision passes through the
     error-agreement checkpoint (:func:`acg_tpu.parallel.erragree.
     agree_status`), so all controllers restart or abort in unison
     instead of one looping while its peers wedge in a collective.

Every detection, restart, and fallback is counted on
:class:`acg_tpu.solvers.stats.SolverStats` and surfaced in the CLI
stats block.
"""

from __future__ import annotations

import dataclasses
import sys
import time

from acg_tpu.errors import BreakdownError


@dataclasses.dataclass(frozen=True)
class RecoveryPolicy:
    """Host-side knobs for detected-breakdown recovery.

    ``max_restarts`` bounds the re-entries per solve (0 = detect only:
    a breakdown raises immediately).  ``backoff`` sleeps before the
    n-th restart for ``backoff * 2**(n-1)`` seconds -- transient
    environmental faults (a flaky link) get time to clear, numerical
    breakdowns restart immediately at the default 0.  ``fallback_comm``
    allows retiring the DMA halo transport for XLA collectives;
    ``fallback_host`` allows the final host-solver rung.
    ``agree_timeout`` bounds the multi-controller restart agreement
    (the ``--err-timeout`` role at recovery checkpoints)."""

    max_restarts: int = 2
    backoff: float = 0.0
    fallback_comm: bool = True
    fallback_host: bool = True
    agree_timeout: float = 120.0
    # the survivability tier's FIRST rung (acg_tpu.checkpoint): on a
    # detected breakdown, roll the loop carry back to the last on-disk
    # snapshot BEFORE spending the restart budget -- a rollback resumes
    # the exact pre-corruption Krylov state, where a restart discards
    # it.  Only consulted by the checkpoint-armed chunk drivers (no
    # snapshot, no rung); 0 disables
    max_rollbacks: int = 1


def adopt_host_stats(st, host_stats) -> None:
    """Fold a host-fallback solve's last-solve stats into the device
    solver's accumulated stats -- shared by both fallback rungs so their
    reports cannot drift apart."""
    st.nsolves += 1
    st.niterations = host_stats.niterations
    st.ntotaliterations += host_stats.niterations
    # the host re-solve usually DOMINATES the wall time of a
    # fallen-back solve; dropping it would corrupt the timing evidence
    st.tsolve += host_stats.tsolve
    for f in ("bnrm2", "x0nrm2", "r0nrm2", "rnrm2", "dxnrm2",
              "converged"):
        setattr(st, f, getattr(host_stats, f))
    st.fexcept_arrays = host_stats.fexcept_arrays


class RecoveryDriver:
    """Per-solve bookkeeping shared by the device solvers' restart loops.

    Owns the attempt counter, the backoff sleeps, the stats counters,
    and the cross-controller agreement; the solvers own program
    re-invocation (their argument layouts differ)."""

    def __init__(self, policy: RecoveryPolicy | None, stats, what: str):
        self.policy = policy
        self.stats = stats
        self.what = what
        self.restarts = 0
        self.rollbacks = 0

    def record(self, event: str, kind: str = "recovery") -> None:
        self.stats.recovery_log.append(event)
        # timestamped twin for the structured stats sink (--stats-json)
        from acg_tpu.telemetry import record_event
        record_event(self.stats, kind, event)
        sys.stderr.write(f"acg-tpu: {self.what}: {event}\n")

    def log_trace_window(self, trace) -> None:
        """Attach the in-loop telemetry's trailing residual window to
        the event log -- the trajectory that led INTO the breakdown is
        exactly what the post-hoc stats block cannot show.  No-op when
        the solve ran without a convergence trace."""
        if trace is None:
            return
        self.record(trace.tail_summary(), kind="trace-window")

    def note_breakdown(self, niter: int) -> None:
        """Account one detected breakdown (counter + metric + event) --
        exactly once per detection, whichever rung then handles it."""
        st = self.stats
        st.nbreakdowns += 1
        from acg_tpu import metrics
        metrics.record_breakdown()
        from acg_tpu.telemetry import record_event
        record_event(st, "breakdown",
                     f"breakdown detected at iteration {niter}")

    def on_rollback(self, niter: int, snapshot_iteration: int) -> bool:
        """The survivability tier's FIRST rung: roll the loop carry back
        to the last snapshot (acg_tpu.checkpoint).  Returns True when
        the policy grants it -- the caller restores the snapshot carry
        and re-enters the chunk loop; False sends the breakdown down
        the existing restart/fallback/abort ladder.  Multi-controller
        the verdict is error-agreed like a restart's (every controller
        rolls back to the SAME agreed snapshot or none does).  Does NOT
        consume the restart budget: a rollback resumes exact Krylov
        state, a restart rebuilds it -- they are different medicines
        and are bounded separately (``max_rollbacks``)."""
        pol = self.policy
        want = (pol is not None
                and self.rollbacks < getattr(pol, "max_rollbacks", 0))
        if not self._agree(0 if want else 1):
            if want:
                self.record("rollback vetoed: a peer controller cannot "
                            "roll back")
            return False
        if not want:
            return False
        self.rollbacks += 1
        self.stats.nrollbacks += 1
        from acg_tpu import metrics
        metrics.record_rollback()
        self.record(f"breakdown at iteration {niter}: rolling back to "
                    f"the snapshot at iteration {snapshot_iteration} "
                    f"(rollback {self.rollbacks}/{pol.max_rollbacks})",
                    kind="rollback")
        return True

    def on_breakdown(self, niter: int, noted: bool = False) -> bool:
        """Account one detected breakdown; returns True when the policy
        grants a restart (after the backoff sleep), False when retries
        are exhausted (caller falls back or raises).  Multi-controller,
        the decision is ERROR-AGREED first: if any controller is out of
        retries (or dead), every controller refuses the restart
        together.  ``noted=True`` (the rollback-rung callers) skips the
        breakdown accounting already done by :meth:`note_breakdown`."""
        st = self.stats
        if not noted:
            self.note_breakdown(niter)
        pol = self.policy
        want_restart = pol is not None and self.restarts < pol.max_restarts
        if not self._agree(0 if want_restart else 1):
            if want_restart:
                self.record("restart vetoed: a peer controller cannot "
                            "continue")
            return False
        if not want_restart:
            return False
        self.restarts += 1
        st.nrestarts += 1
        from acg_tpu import metrics
        metrics.record_restart()
        if pol.backoff > 0:
            time.sleep(pol.backoff * (2 ** (self.restarts - 1)))
        self.record(f"breakdown detected at iteration {niter}; "
                    f"restart {self.restarts}/{pol.max_restarts} from "
                    f"the recomputed true residual", kind="restart")
        return True

    def on_fallback(self, event: str) -> None:
        self.stats.nfallbacks += 1
        from acg_tpu import metrics
        metrics.record_fallback()
        self.record(event, kind="fallback")

    def _agree(self, code: int) -> bool:
        """Cross-controller restart-vs-abort agreement; True = every
        controller can restart.  Single-process: the local verdict."""
        import jax

        if jax.process_count() == 1:
            return code == 0
        from acg_tpu.parallel.erragree import agree_status

        timeout = (self.policy.agree_timeout if self.policy is not None
                   else 120.0)
        return agree_status(code, what=f"{self.what} recovery",
                            timeout=timeout) == 0

    def give_up(self, niter: int, rnrm2: float,
                snapshot: str | None = None):
        """The no-IN-PROCESS-rungs-left exit: a diagnosis-carrying
        exception.  When a committed snapshot exists the diagnosis
        names the next rung OUT of process -- the survivor-mesh
        supervisor (acg_tpu.supervisor, ``--supervise``) relaunches
        with ``--resume`` from exactly that file, so the operator (or
        runbook) reads the recovery action off the error instead of
        grepping docs mid-incident."""
        hint = (f"; a committed snapshot exists at {snapshot} -- "
                f"relaunch with --resume (or run under --supervise "
                f"to automate it)" if snapshot else "")
        return BreakdownError(
            f"{self.what}: breakdown (non-finite residual or "
            f"non-positive p^T A p) at iteration {niter}, residual "
            f"{rnrm2:.3e}; {self.stats.nrestarts} restart(s) exhausted "
            f"and no fallback available{hint}")
