"""Timeline tracing: cross-rank span timeline, profiler-trace analysis,
measured overlap/straggler attribution.

The reference fork's headline observability addition is its trace
harness (``scripts/trace_*.sh`` wrapping every solver in ``nsys profile
-t cuda,nvtx``, SURVEY.md:141,374): the nsys timeline is where exposed
vs hidden collective latency becomes *visible*.  Our ``--trace`` flag
has started/stopped ``jax.profiler`` since PR 2, but nothing ever READ
the capture -- ``--explain`` could only predict communication cost from
static ledgers, never confront the prediction with a measurement.  This
module closes that loop with three legs:

1. **Cross-rank span timeline** (``--timeline FILE``): a lightweight
   span recorder fed by the layers that already know their timings --
   the phase timer's ingest/partition/transfer/compile/solve/writeback
   brackets (:class:`~acg_tpu.telemetry.PhaseTimer`), the survivability
   tier's chunked-dispatch boundaries (the ``k_offset`` chunks of
   ``_solve_ckpt``), and every structured telemetry event
   (:func:`~acg_tpu.telemetry.record_event`) as an instant.  Payloads
   are gathered across controllers over the erragree KV plumbing
   (:func:`~acg_tpu.parallel.erragree.allgather_blobs`) with a
   barrier-timestamp clock alignment, and exported as Chrome
   trace-event JSON -- one pid per PART, Perfetto-loadable -- so a
   multi-part solve renders as the same kind of timeline the reference
   gets from nsys.

2. **Profiler-trace analysis** (:func:`analyze_trace`): parse the
   ``--trace`` capture's ``*.trace.json.gz`` into per-op-class device
   seconds (SpMV vs dot vs collective vs fusion), an
   **overlap-efficiency** score (collective time overlapped with
   compute vs exposed -- the quantity that gates the deep-pipelining
   ROADMAP items, arXiv 1801.04728/1905.06850), and a per-phase
   straggler attribution across ranks.  Where a capture exists the
   measured seconds/op REPLACE ``--profile-ops``' replay estimates and
   feed ``--explain`` a measured-vs-predicted comm verdict; where only
   xplane protos exist (no trace.json) the analysis degrades to a
   self-describing "unavailable" record instead of raising.

3. **Surfaces** in the house style: an append-only ``tracing:`` stats
   section (schema bumped additively to ``acg-tpu-stats/7``),
   ``acg_trace_*`` Prometheus families, and
   ``scripts/trace_report.py``/``scripts/check_timeline.py`` tooling.

Everything is OFF by default.  All recording is host-side bookkeeping
(wall-clock spans around already-existing timing calls), so arming the
recorder cannot perturb the compiled programs -- the lowered HLO stays
byte-identical, pinned in tests/test_hlo_structure.py exactly like the
metrics layer's.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import math
import os
import re
import sys
import threading
import time

TIMELINE_SCHEMA = "acg-tpu-timeline/1"

# a rank (or device line) whose per-phase seconds exceed this multiple
# of the median gets the straggler callout -- THE ratio the cross-rank
# stats aggregation uses, imported so the two callouts can never
# disagree on who is a straggler
from acg_tpu.telemetry import STRAGGLER_RATIO  # noqa: E402

# span categories -> Chrome trace tid (one named row per category, so
# chunk spans never pretend to nest inside the solve phase bracket and
# instants get their own track)
_TID_PHASES, _TID_CHUNKS, _TID_EVENTS = 1, 2, 3
# the solver service's request observatory: the worker's batch spans
# ride one row, and each in-flight request window rides its own lane
# (tid = _TID_REQUEST_BASE + lane, lane assigned by reqtrace)
_TID_WORKER = 4
_TID_REQUEST_BASE = 10
_CAT_TIDS = {"phase": _TID_PHASES, "chunk": _TID_CHUNKS,
             "ckpt": _TID_CHUNKS, "event": _TID_EVENTS,
             "worker": _TID_WORKER, "request": _TID_REQUEST_BASE}

# -- the span recorder ---------------------------------------------------

_lock = threading.Lock()
_armed = False
_spans: list[dict] = []
_instants: list[dict] = []


def arm() -> None:
    """Arm the process-wide span recorder (``--timeline``).  Host-side
    bookkeeping only; the hooks in telemetry/checkpoint stay cheap
    early-returns until this is called."""
    global _armed
    _armed = True


def disarm() -> None:
    """Disarm AND clear -- in-process callers (tests, library use) must
    not leak one invocation's spans into the next."""
    global _armed
    _armed = False
    with _lock:
        _spans.clear()
        _instants.clear()


def armed() -> bool:
    return _armed


def record_span(name: str, t0: float, t1: float, cat: str = "phase",
                part: int | None = None, **attrs) -> None:
    """One completed span in unix-epoch seconds (``time.time()`` -- the
    only clock that can be aligned ACROSS controllers; perf_counter
    epochs differ per process)."""
    if not _armed:
        return
    span = {"name": str(name), "t0": float(t0), "t1": float(max(t1, t0)),
            "cat": str(cat)}
    if part is not None:
        span["part"] = int(part)
    if attrs:
        span["args"] = {k: v for k, v in attrs.items() if v is not None}
    with _lock:
        _spans.append(span)
    from acg_tpu import metrics
    metrics.record_trace_span(cat)


def record_phase_span(name: str, seconds: float) -> None:
    """The phase-timer hook: phases report ``(name, seconds)`` at phase
    END, so the span is ``[now - seconds, now]`` on the wall clock."""
    if not _armed:
        return
    t1 = time.time()
    record_span(name, t1 - max(float(seconds), 0.0), t1, cat="phase")


def record_instant(name: str, detail: str | None = None,
                   part: int | None = None) -> None:
    """One instant event (the telemetry tier's structured events --
    breakdown/restart/rollback/resume/drift/... -- as timeline pins)."""
    if not _armed:
        return
    inst = {"name": str(name), "t": time.time()}
    if detail:
        inst["detail"] = str(detail)
    if part is not None:
        inst["part"] = int(part)
    with _lock:
        _instants.append(inst)
    from acg_tpu import metrics
    metrics.record_trace_span("event")


def nspans() -> int:
    with _lock:
        return len(_spans) + len(_instants)


# -- profiler start/stop (the hoisted --trace block) ---------------------

@contextlib.contextmanager
def profiler_trace(trace_dir):
    """``jax.profiler.start_trace``/``stop_trace`` around a block --
    the ONE copy of what cli.py previously open-coded at every solve
    mode.  ``None`` is a no-op; a failed start warns and runs the body
    unprofiled (a solve must never die for its observability); stop
    always runs on the error path too -- that is when the capture is
    most needed."""
    if not trace_dir:
        yield
        return
    import jax

    started = False
    try:
        jax.profiler.start_trace(str(trace_dir))
        started = True
    except Exception as e:  # noqa: BLE001 -- profile-or-not, never sink
        sys.stderr.write(f"acg-tpu: --trace {trace_dir}: profiler "
                         f"start failed ({type(e).__name__}: {e}); "
                         f"continuing without a capture\n")
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001
                sys.stderr.write(f"acg-tpu: --trace {trace_dir}: "
                                 f"profiler stop failed "
                                 f"({type(e).__name__}: {e})\n")


# -- cross-rank gather + clock alignment ---------------------------------

def local_payload(parts=None) -> dict:
    """This controller's timeline contribution: its recorded spans and
    instants plus the part ids it owns (``parts=None`` = unpartitioned:
    the spans land on one pid)."""
    import jax

    with _lock:
        spans = [dict(s) for s in _spans]
        instants = [dict(i) for i in _instants]
    return {"process": int(jax.process_index()),
            "parts": ([int(p) for p in parts] if parts is not None
                      else None),
            "spans": spans, "instants": instants}


def align_payloads(payloads: list[dict]) -> dict:
    """Barrier-timestamp clock alignment, in place.

    Every payload carries ``t_barrier`` -- ``time.time()`` taken
    immediately after ALL ranks exited the same allgather barrier, so
    the true event is simultaneous up to barrier-exit jitter and any
    difference is clock skew.  Shifting rank r by
    ``max(t_barrier) - t_barrier[r]`` (always >= 0) lands every rank on
    the slowest clock: after alignment the barrier stamps are EQUAL, so
    no span can precede a peer's view of the same wall instant -- no
    negative inter-rank skew survives."""
    stamps = [p.get("t_barrier") for p in payloads]
    known = [s for s in stamps if s is not None]
    info = {"ranks": len(payloads), "aligned": len(known) > 1,
            "max_skew_s": (max(known) - min(known)) if known else 0.0}
    if len(known) < 2:
        return info
    ref = max(known)
    for p in payloads:
        tb = p.get("t_barrier")
        if tb is None:
            continue
        off = ref - tb
        p["clock_offset_s"] = off
        if off == 0.0:
            continue
        for s in p.get("spans", []):
            s["t0"] += off
            s["t1"] += off
        for i in p.get("instants", []):
            i["t"] += off
        p["t_barrier"] = ref
    return info


def gather_timeline(parts=None, timeout: float = 120.0,
                    collective: bool = True
                    ) -> tuple[list[dict], dict]:
    """``(payloads, clock_info)`` -- every controller's spans, clock
    aligned.  COLLECTIVE (every controller must call it at the same
    point); error paths pass ``collective=False`` and get the local
    payload alone (a one-sided failure must not enter a gather its
    peers may never reach -- the erragree rationale).  Never raises
    and never returns None: a failed gather degrades to this
    controller's local payload."""
    import jax

    payload = local_payload(parts=parts)
    n = jax.process_count()
    if n == 1 or not collective:
        payload["t_barrier"] = time.time()
        return [payload], {"ranks": 1, "aligned": False,
                           "max_skew_s": 0.0}
    from acg_tpu.parallel.erragree import allgather_blobs, barrier

    try:
        # round 1 is pure barrier: after it returns, all ranks are
        # within barrier-exit jitter of the same instant -- the stamp
        # taken THERE is the clock-alignment reference
        payload["t_barrier"] = barrier(tag="timeline-sync",
                                       timeout=timeout)
        blobs = allgather_blobs(json.dumps(payload), tag="timeline",
                                timeout=timeout)
    except Exception as e:  # noqa: BLE001 -- the timeline is
        # best-effort: a failed gather must not take down a solve that
        # succeeded (gather_rank_stats discipline)
        sys.stderr.write(f"acg-tpu: timeline gather failed "
                         f"({type(e).__name__}); writing this "
                         f"controller's spans only\n")
        return [payload], {"ranks": 1, "aligned": False,
                           "max_skew_s": 0.0}
    payloads = [json.loads(b) for b in blobs]
    info = align_payloads(payloads)
    return payloads, info


# -- Chrome trace-event export -------------------------------------------

def export_chrome_trace(path, payloads: list[dict], nparts: int = 1,
                        clock: dict | None = None) -> dict:
    """Write the gathered spans as Chrome trace-event JSON (Perfetto /
    chrome://tracing loadable): one pid per PART (pid = part + 1; rank
    named in the process metadata), spans as complete ``X`` events on
    per-category rows, telemetry events as instants.  A controller-wide
    span (no ``part``) describes every part that controller owns -- the
    SPMD program runs them in lockstep -- so it is replicated onto each
    owned pid, exactly how an nsys timeline shows one row per GPU for a
    fully bulk-synchronous phase.  Returns the summary dict that lands
    in the ``tracing:`` stats section."""
    events: list[dict] = []
    all_t: list[float] = []
    for p in payloads:
        for s in p.get("spans", []):
            all_t.append(s["t0"])
        for i in p.get("instants", []):
            all_t.append(i["t"])
    origin = min(all_t) if all_t else 0.0

    pids_seen: set[int] = set()
    # service-timeline tracks discovered from the spans themselves
    # (the worker row and one lane per concurrent request window) --
    # named AFTER the walk, once we know which exist
    extra_tracks: set[tuple[int, int, str]] = set()
    nspans_out = 0
    for p in payloads:
        rank = int(p.get("process", 0))
        parts = p.get("parts")
        if parts is None:
            parts = [rank]
        parts = [int(q) for q in parts] or [rank]
        for part in parts:
            pid = part + 1
            if pid in pids_seen:
                continue
            pids_seen.add(pid)
            events.append({"ph": "M", "pid": pid, "name": "process_name",
                           "args": {"name": f"part {part} "
                                            f"(rank {rank})"}})
            events.append({"ph": "M", "pid": pid,
                           "name": "process_sort_index",
                           "args": {"sort_index": pid}})
            for tid, tname in ((_TID_PHASES, "phases"),
                               (_TID_CHUNKS, "chunks"),
                               (_TID_EVENTS, "events")):
                events.append({"ph": "M", "pid": pid, "tid": tid,
                               "name": "thread_name",
                               "args": {"name": tname}})
        for s in p.get("spans", []):
            targets = ([int(s["part"]) + 1] if s.get("part") is not None
                       else [q + 1 for q in parts])
            cat = s.get("cat", "phase")
            tid = (_TID_CHUNKS if s["name"] == "ckpt"
                   else _CAT_TIDS.get(cat, _TID_PHASES))
            if cat == "request":
                lane = (s.get("args") or {}).get("lane")
                tid = _TID_REQUEST_BASE + (int(lane) if isinstance(
                    lane, (int, float)) else 0)
            for pid in targets:
                if cat == "worker":
                    extra_tracks.add((pid, tid, "serve worker"))
                elif cat == "request":
                    extra_tracks.add(
                        (pid, tid,
                         f"request lane {tid - _TID_REQUEST_BASE}"))
                ev = {"ph": "X", "pid": pid, "tid": tid,
                      "name": s["name"], "cat": cat,
                      "ts": (s["t0"] - origin) * 1e6,
                      "dur": max((s["t1"] - s["t0"]) * 1e6, 0.001)}
                if s.get("args"):
                    ev["args"] = s["args"]
                events.append(ev)
                nspans_out += 1
        for i in p.get("instants", []):
            targets = ([int(i["part"]) + 1] if i.get("part") is not None
                       else [q + 1 for q in parts])
            for pid in targets:
                ev = {"ph": "i", "pid": pid, "tid": _TID_EVENTS,
                      "name": i["name"], "s": "p",
                      "ts": (i["t"] - origin) * 1e6}
                if i.get("detail"):
                    ev["args"] = {"detail": i["detail"]}
                events.append(ev)
    for pid, tid, tname in sorted(extra_tracks):
        events.append({"ph": "M", "pid": pid, "tid": tid,
                       "name": "thread_name", "args": {"name": tname}})
    # monotone ts per (pid, tid) track by construction of the writer,
    # not by luck of recording order (check_timeline.py validates it)
    events.sort(key=lambda e: (e.get("ph") != "M", e["pid"],
                               e.get("tid", 0), e.get("ts", 0.0)))
    doc = {
        "displayTimeUnit": "ms",
        "metadata": {
            "schema": TIMELINE_SCHEMA,
            "origin_unix_s": origin,
            "nparts": int(nparts),
            "nranks": len(payloads),
            "clock": clock or {"ranks": len(payloads),
                               "aligned": False, "max_skew_s": 0.0},
        },
        "traceEvents": events,
    }
    own = isinstance(path, (str, bytes)) or hasattr(path, "__fspath__")
    f = open(path, "w") if own else path
    try:
        json.dump(doc, f)
        f.write("\n")
    finally:
        if own:
            f.close()
    summary = {"file": os.fspath(path) if own else "<stream>",
               "schema": TIMELINE_SCHEMA,
               "nspans": nspans_out, "nparts": len(pids_seen),
               "nranks": len(payloads),
               "clock_max_skew_s": float((clock or {}).get("max_skew_s",
                                                           0.0))}
    from acg_tpu import metrics
    metrics.record_timeline_export()
    return summary


def read_timeline(path) -> dict:
    """Parse a ``--timeline`` file back; raises ValueError when it is
    not an acg-tpu timeline (the content-sniffing classifiers in
    plot_convergence/trace_report dispatch on this)."""
    with open(path) as f:
        doc = json.load(f)
    if (not isinstance(doc, dict)
            or not isinstance(doc.get("traceEvents"), list)):
        raise ValueError("not a Chrome trace-event document")
    return doc


# -- profiler-trace analysis ---------------------------------------------

# HLO op INSTANCES only (full match, optional "%"/-start/-done/".N"
# decorations): substring search would misfile XLA compile-pass events
# like "batch-dot-simplification" or "all-reduce-folder" -- a capture
# contains the compiler's own timeline too, and pass time is not op
# time.  First match wins: the collective classes outrank "dot" (an
# all-reduce is not a dot product).
_HLO_PATTERNS: tuple[tuple[str, re.Pattern], ...] = (
    ("allreduce", re.compile(
        r"%?(all[-_.]?reduce|reduce[-_.]?scatter)"
        r"([-_.](start|done))?[.\d]*$", re.I)),
    ("halo", re.compile(
        r"%?(all[-_.]?to[-_.]?all|collective[-_.]?permute)"
        r"([-_.](start|done))?[.\d]*$", re.I)),
    ("dot", re.compile(r"%?(dot|gemm|convolution)[.\d]*$", re.I)),
    # bare "fusion" is ALSO an XLA pass name -- only the numbered HLO
    # instances ("fusion.3", "loop_fusion.12") count as device op time
    ("fusion", re.compile(r"%?(loop_|input_|output_)?fusion\.\d+$",
                          re.I)),
    ("copy", re.compile(r"%?(copy|transpose|bitcast)"
                        r"([-_.](start|done))?[.\d]*$", re.I)),
)
# keyword classes safe as substrings anywhere (our own kernel/program
# names; these tokens never appear in XLA pass names)
_KEYWORD_PATTERNS: tuple[tuple[str, re.Pattern], ...] = (
    ("gemv", re.compile(r"spmv|matvec|gemv", re.I)),
    ("allreduce", re.compile(r"\bpsum\b", re.I)),
    ("halo", re.compile(r"ppermute|halo_exchange", re.I)),
)
# collective KIND sub-classification (the commbench observatory's
# per-kind confrontation: acg_tpu.commbench fits one alpha-beta model
# per kind, so the capture must report measured seconds per kind too,
# not one pooled "collective" figure).  First match wins; the fallback
# maps the coarse class (allreduce -> all_reduce, halo -> all_to_all)
_COLLECTIVE_KIND_PATTERNS: tuple[tuple[str, re.Pattern], ...] = (
    # "dma" must match halo_exchange_dma / pallas put kernels but NOT
    # the plain halo_exchange all_to_all transport program name
    ("dma", re.compile(r"dma|pallas", re.I)),
    ("all_to_all", re.compile(r"all[-_.]?to[-_.]?all", re.I)),
    ("collective_permute", re.compile(
        r"collective[-_.]?permute|ppermute", re.I)),
    ("all_reduce", re.compile(
        r"all[-_.]?reduce|reduce[-_.]?scatter|psum", re.I)),
)


def _collective_kind(name: str, cls: str) -> str:
    for kind, pat in _COLLECTIVE_KIND_PATTERNS:
        if pat.search(name):
            return kind
    return "all_reduce" if cls == "allreduce" else "all_to_all"


_PJIT_RE = re.compile(r"^(?:PjitFunction|jit_?)\(?([^)]*)\)?$")
_PHASES = ("ingest", "partition", "transfer", "compile", "solve",
           "ckpt", "writeback")


def _classify_op(name: str) -> str | None:
    m = _PJIT_RE.match(name)
    if m:
        inner = m.group(1)
        for cls, pat in _HLO_PATTERNS + _KEYWORD_PATTERNS:
            if pat.search(inner):
                return cls
        # a compiled-program dispatch (the whole fused solve on CPU
        # captures, where XLA emits no per-HLO-op device events)
        return "program"
    for cls, pat in _HLO_PATTERNS:
        if pat.fullmatch(name):
            return cls
    for cls, pat in _KEYWORD_PATTERNS:
        if pat.search(name):
            return cls
    return None


def _union_seconds(intervals: list[tuple[float, float]]) -> float:
    if not intervals:
        return 0.0
    intervals.sort()
    total = 0.0
    cur0, cur1 = intervals[0]
    for a, b in intervals[1:]:
        if a > cur1:
            total += cur1 - cur0
            cur0, cur1 = a, b
        else:
            cur1 = max(cur1, b)
    return total + (cur1 - cur0)


def _subtract_seconds(base: list[tuple[float, float]],
                      cover: list[tuple[float, float]]) -> float:
    """Seconds of ``union(base)`` NOT covered by ``union(cover)`` --
    the exposed-collective computation."""
    return _union_seconds(list(base)) - _overlap_seconds(base, cover)


def _overlap_seconds(a: list[tuple[float, float]],
                     b: list[tuple[float, float]]) -> float:
    if not a or not b:
        return 0.0
    # merge each side first so double-covered stretches count once
    def merged(iv):
        iv = sorted(iv)
        out = [list(iv[0])]
        for s, e in iv[1:]:
            if s > out[-1][1]:
                out.append([s, e])
            else:
                out[-1][1] = max(out[-1][1], e)
        return out

    am, bm = merged(a), merged(b)
    i = j = 0
    total = 0.0
    while i < len(am) and j < len(bm):
        lo = max(am[i][0], bm[j][0])
        hi = min(am[i][1], bm[j][1])
        if hi > lo:
            total += hi - lo
        if am[i][1] < bm[j][1]:
            i += 1
        else:
            j += 1
    return total


def find_capture(trace_dir) -> dict:
    """Locate the profiler artifacts under a ``--trace`` dir: the
    Chrome-format ``*.trace.json(.gz)`` files (one per host) and the
    xplane protos (schema we deliberately do NOT parse -- no
    tensorflow/xprof dependency in this container)."""
    d = os.fspath(trace_dir)
    traces = sorted(glob.glob(os.path.join(d, "**", "*.trace.json.gz"),
                              recursive=True)
                    + glob.glob(os.path.join(d, "**", "*.trace.json"),
                                recursive=True))
    xplanes = sorted(glob.glob(os.path.join(d, "**", "*.xplane.pb"),
                               recursive=True))
    return {"dir": d, "trace_json": traces, "xplane": xplanes}


def analyze_trace(trace_dir) -> dict:
    """Parse a ``--trace`` capture into measured per-op-class device
    seconds, the overlap-efficiency score, per-phase seconds, and the
    cross-rank straggler attribution.

    Degrades instead of raising: a missing/empty dir, an xplane-only
    capture (no trace.json the stdlib can read), or a corrupt file all
    return ``{"available": False, "why": ...}`` -- the callers print
    the why and keep the static verdict (the --explain contract)."""
    try:
        cap = find_capture(trace_dir)
    except OSError as e:
        return {"available": False, "why": f"{type(e).__name__}: {e}"}
    if not cap["trace_json"]:
        why = ("capture has xplane protos only -- no trace.json the "
               "stdlib can parse (xprof schema unavailable here)"
               if cap["xplane"] else
               f"no profiler capture under {cap['dir']} (profiler "
               f"unavailable or start failed)")
        return {"available": False, "why": why,
                "xplane_files": len(cap["xplane"])}

    op_s: dict[str, float] = {}
    op_solve_s: dict[str, float] = {}
    kind_s: dict[str, float] = {}
    kind_solve_s: dict[str, float] = {}
    phase_s: dict[str, float] = {}
    per_rank: list[dict] = []
    exposed = 0.0
    nsolve_windows = 0
    for path in cap["trace_json"]:
        try:
            opener = gzip.open if path.endswith(".gz") else open
            with opener(path, "rt") as f:
                doc = json.load(f)
            events = doc.get("traceEvents", [])
        except (OSError, ValueError) as e:
            return {"available": False,
                    "why": f"{os.path.basename(path)}: "
                           f"{type(e).__name__}: {e}"}
        rank_phase: dict[str, float] = {}
        rank_busy: list[tuple[float, float]] = []
        # pass 1: the acg:* phase brackets.  The "solve" windows matter
        # beyond reporting: a capture also contains the WARMUP solves
        # (full program executions inside the compile bracket) and
        # every --soak repeat, so per-op attribution must be windowed
        # to the timed solve(s) or the "measured" seconds overstate the
        # solve the op census describes
        solve_iv: list[tuple[float, float]] = []
        for e in events:
            if e.get("ph") != "X":
                continue
            name = str(e.get("name", ""))
            pname = name[4:] if name.startswith("acg:") else name
            if pname not in _PHASES:
                continue
            dur = float(e.get("dur", 0.0)) * 1e-6
            ts = float(e.get("ts", 0.0)) * 1e-6
            phase_s[pname] = phase_s.get(pname, 0.0) + dur
            rank_phase[pname] = rank_phase.get(pname, 0.0) + dur
            if pname == "solve":
                solve_iv.append((ts, ts + dur))
        nsolve_windows += len(solve_iv)
        # pass 2: op-class events.  The overlap algebra stays PER FILE:
        # each host's capture has its own profiler timebase (and its
        # own devices) -- pooling intervals across files would let one
        # host's compute "hide" another host's exposed collectives
        coll_iv: list[tuple[float, float]] = []
        comp_iv: list[tuple[float, float]] = []
        for e in events:
            if e.get("ph") != "X":
                continue
            name = str(e.get("name", ""))
            if name.startswith("$"):
                continue  # python-interpreter frames
            pname = name[4:] if name.startswith("acg:") else name
            if pname in _PHASES:
                continue
            cls = _classify_op(name)
            if cls is None:
                continue
            dur = float(e.get("dur", 0.0)) * 1e-6
            ts = float(e.get("ts", 0.0)) * 1e-6
            op_s[cls] = op_s.get(cls, 0.0) + dur
            mid = ts + dur / 2.0
            in_solve = any(a <= mid <= b for a, b in solve_iv)
            if in_solve:
                op_solve_s[cls] = op_solve_s.get(cls, 0.0) + dur
            iv = (ts, ts + dur)
            rank_busy.append(iv)
            if cls in ("allreduce", "halo"):
                # per-KIND breakdown (all_reduce / all_to_all /
                # collective_permute / dma): the row the commbench
                # alpha-beta fits are confronted with, kind by kind
                kind = _collective_kind(name, cls)
                kind_s[kind] = kind_s.get(kind, 0.0) + dur
                if in_solve:
                    kind_solve_s[kind] = (kind_solve_s.get(kind, 0.0)
                                          + dur)
                coll_iv.append(iv)
            else:
                comp_iv.append(iv)
        if coll_iv:
            exposed += _subtract_seconds(coll_iv, comp_iv)
        rank = os.path.basename(path).split(".")[0]
        per_rank.append({"rank": rank,
                         "phase_seconds": rank_phase,
                         "busy_seconds": _union_seconds(rank_busy)})

    coll_total = op_s.get("allreduce", 0.0) + op_s.get("halo", 0.0)
    overlap_eff = (1.0 - exposed / coll_total) if coll_total > 0 else None

    straggler = _phase_straggler(per_rank)
    return {"available": True, "dir": cap["dir"],
            "nfiles": len(cap["trace_json"]),
            "xplane_files": len(cap["xplane"]),
            "op_seconds": {k: round(v, 9)
                           for k, v in sorted(op_s.items())},
            "op_seconds_in_solve": {k: round(v, 9)
                                    for k, v in sorted(op_solve_s
                                                       .items())},
            "solve_windows": nsolve_windows,
            "collective_seconds": round(coll_total, 9),
            "collective_seconds_in_solve": round(
                op_solve_s.get("allreduce", 0.0)
                + op_solve_s.get("halo", 0.0), 9),
            "collective_kind_seconds": {k: round(v, 9)
                                        for k, v in sorted(
                                            kind_s.items())},
            "collective_kind_seconds_in_solve": {
                k: round(v, 9)
                for k, v in sorted(kind_solve_s.items())},
            "exposed_collective_seconds": round(exposed, 9),
            "overlap_efficiency": (round(overlap_eff, 6)
                                   if overlap_eff is not None else None),
            "phase_seconds": {k: round(phase_s[k], 9)
                              for k in _PHASES if k in phase_s},
            "per_rank": per_rank,
            "straggler": straggler}


def _phase_straggler(per_rank: list[dict]) -> dict | None:
    """Which rank's solve phase is slowest, and by how much over the
    median -- the measured twin of telemetry.aggregate_ranks' wall-time
    callout.  None below 2 ranks or under the STRAGGLER_RATIO bar."""
    import statistics

    solves = [(r.get("phase_seconds", {}).get("solve", 0.0),
               r.get("rank", str(i))) for i, r in enumerate(per_rank)]
    solves = [(t, r) for t, r in solves if t > 0]
    if len(solves) < 2:
        return None
    solves.sort()
    # the TRUE median (mean of the middle two on even counts) --
    # telemetry.aggregate_ranks uses np.median, and the upper-middle
    # shortcut could never flag a straggler across exactly 2 hosts
    med = statistics.median(t for t, _ in solves)
    worst_t, worst_r = solves[-1]
    if med <= 0 or worst_t <= STRAGGLER_RATIO * med:
        return None
    return {"rank": worst_r, "phase": "solve",
            "seconds": round(worst_t, 9),
            "ratio_to_median": round(worst_t / med, 4)}


# -- stats/ops/metrics attachment ----------------------------------------

# analysis op classes -> SolverStats.ops rows the measured seconds may
# REPLACE ("gemv" is the stats block's SpMV row; "fusion"/"program"/
# "copy" have no row and stay in the tracing: section only)
_MEASURED_OPS = ("gemv", "dot", "allreduce", "halo")


def attach(stats, analysis: dict | None,
           timeline: dict | None = None) -> None:
    """Fill the append-only ``tracing:`` stats section (and its
    ``--stats-json`` twin) from a capture analysis and/or a timeline
    export summary, and -- where the capture measured an op class the
    replay tier could only estimate -- overwrite that op row's seconds
    with the MEASURED ones.  A disarmed run records nothing and the
    report stays byte-identical (the costmodel/soak discipline)."""
    if analysis is not None:
        sec = {"available": bool(analysis.get("available"))}
        if analysis.get("available"):
            sec.update({
                "capture_files": analysis.get("nfiles", 0),
                "op_seconds": dict(analysis.get("op_seconds", {})),
                "collective_seconds": analysis.get("collective_seconds",
                                                   0.0),
                "exposed_collective_seconds":
                    analysis.get("exposed_collective_seconds", 0.0),
            })
            if analysis.get("collective_kind_seconds"):
                sec["collective_kind_seconds"] = dict(
                    analysis["collective_kind_seconds"])
            if analysis.get("overlap_efficiency") is not None:
                sec["overlap_efficiency"] = \
                    analysis["overlap_efficiency"]
            if analysis.get("phase_seconds"):
                sec["phase_seconds"] = dict(analysis["phase_seconds"])
            strag = analysis.get("straggler")
            if strag:
                sec["straggler"] = dict(strag)
            filled = apply_measured_ops(stats, analysis)
            if filled:
                # provenance, not a claim that a replay ran: these rows
                # now hold capture-measured seconds (superseding the
                # --profile-ops replay estimate whenever one was there)
                sec["ops_source"] = ("trace (" + ", ".join(filled)
                                     + " measured from the capture's "
                                       "solve windows)")
        else:
            sec["why"] = analysis.get("why", "unavailable")
        stats.tracing.update(sec)
        from acg_tpu import metrics
        metrics.record_trace_analysis(analysis)
    if timeline is not None:
        stats.tracing["timeline"] = dict(timeline)


def apply_measured_ops(stats, analysis: dict) -> list[str]:
    """Overwrite ``stats.ops[cls].t`` with the capture's measured
    seconds for every op class the capture actually resolved (TPU
    captures carry per-op device events; CPU captures usually only
    carry whole-program dispatches, so nothing is overwritten and the
    replay estimates stand).  Returns the classes replaced.

    Only events inside the ``solve`` phase bracket(s) count: a capture
    also contains the WARMUP solves (full program executions inside
    the ``compile`` bracket), which would inflate the "measured"
    seconds by (warmup+1)x against the census.  The in-solve seconds
    are summed over ALL solve windows -- the op rows' ``n``/``bytes``
    accumulate across ``--soak`` repeats and the timed windows do too,
    the same cumulative convention as the replay tier's
    ``t = per_call * n``, so GB/s and the ``other`` residual stay
    consistent.  A capture without solve brackets (foreign producer)
    overwrites nothing."""
    if int(analysis.get("solve_windows", 0)) < 1:
        return []
    filled = []
    for cls in _MEASURED_OPS:
        secs = float(analysis.get("op_seconds_in_solve",
                                  {}).get(cls, 0.0))
        if secs > 0 and cls in stats.ops and stats.ops[cls].n > 0:
            stats.ops[cls].t = secs
            filled.append(cls)
    return filled


def format_analysis(analysis: dict) -> list[str]:
    """Human lines for the --explain measured section and
    trace_report.py -- one writer so the two cannot drift."""
    if not analysis.get("available"):
        return [f"  (no usable capture: "
                f"{analysis.get('why', 'unavailable')})"]
    lines = []
    ops = analysis.get("op_seconds", {})
    if ops:
        width = max(len(k) for k in ops)
        for cls, secs in ops.items():
            lines.append(f"  {cls:<{width}}: {secs:.6f} s")
    else:
        lines.append("  (no per-op device events in this capture -- "
                     "CPU backends emit whole-program dispatches only)")
    kinds = analysis.get("collective_kind_seconds") or {}
    if kinds:
        lines.append("  collectives by kind: "
                     + ", ".join(f"{k} {v:.6f}s"
                                 for k, v in kinds.items()))
    coll = analysis.get("collective_seconds", 0.0)
    eff = analysis.get("overlap_efficiency")
    if eff is not None:
        lines.append(f"  overlap efficiency: {eff:.2%} of "
                     f"{coll:.6f} s collective time hidden under "
                     f"compute ({analysis.get('exposed_collective_seconds', 0.0):.6f} s exposed)")
    else:
        lines.append("  overlap efficiency: n/a (no collective events "
                     "in capture)")
    ph = analysis.get("phase_seconds", {})
    if ph:
        lines.append("  phases: " + ", ".join(f"{k} {v:.3f}s"
                                              for k, v in ph.items()))
    strag = analysis.get("straggler")
    if strag:
        lines.append(f"  straggler: {strag['rank']} "
                     f"({strag['ratio_to_median']:.2f}x median "
                     f"{strag['phase']} time)")
    elif len(analysis.get("per_rank", [])) > 1:
        lines.append(f"  no straggler across "
                     f"{len(analysis['per_rank'])} ranks (all within "
                     f"{STRAGGLER_RATIO:.1f}x of median)")
    return lines


def measured_comm_line(analysis: dict, predicted_comm_s: float,
                       label: str = "solve") -> str:
    """The measured-vs-predicted comm verdict line ``--explain``
    appends when a capture exists: the static ledger's predicted
    collective seconds confronted with the capture's measured ones.
    The measurement is windowed to the ``solve`` phase brackets when
    the capture has them -- the ledger prices the TIMED iterations,
    and a capture also holds the warmup solves' collectives (a
    systematic (warmup+1)x bias that would sit exactly on the
    consistent/underestimates boundary)."""
    windowed = int(analysis.get("solve_windows", 0)) >= 1
    meas = float(analysis.get("collective_seconds_in_solve", 0.0)
                 if windowed else
                 analysis.get("collective_seconds", 0.0))
    if meas <= 0:
        return (f"  comm: predicted {predicted_comm_s:.3e} s "
                f"({label}); capture measured no collective device "
                f"events{' in the solve windows' if windowed else ''} "
                f"-- nothing to confront the ledger with")
    ratio = meas / predicted_comm_s if predicted_comm_s > 0 else math.inf
    verdict = ("ledger consistent" if 0.5 <= ratio <= 2.0 else
               "ledger underestimates" if ratio > 2.0 else
               "ledger overestimates")
    return (f"  comm: predicted {predicted_comm_s:.3e} s vs measured "
            f"{meas:.3e} s collective device time"
            f"{' (solve windows)' if windowed else ''} "
            f"({ratio:.2f}x) -- {verdict}")
