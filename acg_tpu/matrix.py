"""Symmetric sparse matrices in CSR form (host-side, numpy).

Rebuilds the reference's ``acg/symcsrmatrix.c`` (SURVEY.md component #8):
the canonical storage is the *packed upper triangle* in CSR form (diagonal
plus strictly-upper entries); derived *full storage* CSR is built on demand
for SpMV, optionally with a diagonal shift (the ``--epsilon`` option,
``symcsrmatrix.c:760-862``).  Partitioned matrices additionally split full
storage into an owned x owned block and an owned x ghost block — that split
lives in :mod:`acg_tpu.graph` / :mod:`acg_tpu.parallel`, which consume this
class.

scipy.sparse provides the compiled host SpMV engine (the role of the
4x-unrolled OpenMP loop at ``symcsrmatrix.c:863-1005``); the structure and
invariants (packed canonical form, dedupe, symmetry expansion) are ours.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import scipy.sparse as sp

from acg_tpu.errors import AcgError, ErrorCode
from acg_tpu.io.mtxfile import IDX_DTYPE, MtxFile


@dataclasses.dataclass
class SymCsrMatrix:
    """A symmetric sparse matrix stored as packed upper-triangle CSR.

    Invariants (matching ``symcsrmatrix.h:62-292``):
      * ``prowptr``/``pcolidx``/``pa`` hold each symmetric entry once with
        ``col >= row`` (diagonal included), rows sorted, no duplicates.
      * ``nrows == ncols`` (SPD systems only).
    """

    nrows: int
    prowptr: np.ndarray  # (nrows+1,) int64
    pcolidx: np.ndarray  # (pnnz,)   int64, col >= row
    pa: np.ndarray       # (pnnz,)   float64

    @property
    def pnnz(self) -> int:
        return int(self.pcolidx.size)

    @property
    def nnz_full(self) -> int:
        """Number of nonzeros in the logically-full symmetric matrix."""
        ndiag = int(np.sum(self.pcolidx == np.repeat(
            np.arange(self.nrows), np.diff(self.prowptr))))
        return 2 * self.pnnz - ndiag

    # -- construction ---------------------------------------------------

    @classmethod
    def from_coo(cls, nrows: int, rowidx, colidx, vals) -> "SymCsrMatrix":
        """Build from COO triplets of a symmetric matrix.

        Accepts either full storage (both triangles present) or one-triangle
        storage (upper or lower); duplicates are summed except when the same
        off-diagonal entry appears in both triangles, in which case the two
        mirror entries must agree and one is kept.
        """
        rowidx = np.asarray(rowidx, dtype=IDX_DTYPE)
        colidx = np.asarray(colidx, dtype=IDX_DTYPE)
        vals = np.asarray(vals, dtype=np.float64)
        from acg_tpu import _native
        if _native.available() and rowidx.size:
            try:
                pr, pc, pa = _native.sym_csr_from_coo(nrows, rowidx, colidx,
                                                      vals)
                return cls(nrows=nrows, prowptr=pr, pcolidx=pc, pa=pa)
            except _native.NativeParseError as e:
                if e.code == -3:
                    raise AcgError(ErrorCode.INDEX_OUT_OF_BOUNDS,
                                   "COO indices out of range")
                # key overflow for huge nrows: numpy path below
        # map everything to the upper triangle
        r = np.minimum(rowidx, colidx)
        c = np.maximum(rowidx, colidx)
        # dedupe via sparse assembly; mirrored duplicates would double
        # off-diagonal values, so detect full storage and halve those.
        upper = sp.coo_matrix((vals, (r, c)), shape=(nrows, nrows)).tocsr()
        upper.sum_duplicates()
        offdiag_in = rowidx != colidx
        # full storage iff any strictly-lower entry present
        has_lower = bool(np.any(rowidx[offdiag_in] > colidx[offdiag_in]))
        has_upper = bool(np.any(rowidx[offdiag_in] < colidx[offdiag_in]))
        if has_lower and has_upper:
            # both triangles were present: off-diagonal sums counted twice
            coo = upper.tocoo()
            off = coo.row != coo.col
            coo.data[off] *= 0.5
            upper = coo.tocsr()
        return cls(nrows=nrows, prowptr=upper.indptr.astype(IDX_DTYPE),
                   pcolidx=upper.indices.astype(IDX_DTYPE), pa=upper.data)

    @classmethod
    def from_mtx(cls, mtx: MtxFile) -> "SymCsrMatrix":
        if mtx.object != "matrix" or mtx.format != "coordinate":
            raise AcgError(ErrorCode.NOT_SUPPORTED, "need a coordinate matrix")
        if mtx.nrows != mtx.ncols:
            raise AcgError(ErrorCode.INVALID_VALUE, "matrix must be square")
        if mtx.symmetry not in ("symmetric", "general"):
            raise AcgError(ErrorCode.NOT_SUPPORTED, f"symmetry {mtx.symmetry}")
        r, c, v = mtx.to_coo()
        return cls.from_coo(mtx.nrows, r, c, v)

    # -- full storage ----------------------------------------------------

    def to_csr(self, epsilon: float = 0.0) -> sp.csr_matrix:
        """Full-storage CSR with optional diagonal shift A + eps*I.

        Equivalent of ``acgsymcsrmatrix_dsymv_init`` (``symcsrmatrix.c:760``).
        """
        from acg_tpu import _native
        if _native.available() and self.pnnz:
            fr, fc, fa = _native.sym_csr_expand(self.nrows, self.prowptr,
                                                self.pcolidx, self.pa,
                                                epsilon)
            idt = (np.int32 if self.nrows < 2**31 and fr[-1] < 2**31
                   else np.int64)
            return sp.csr_matrix((fa, fc.astype(idt, copy=False),
                                  fr.astype(idt, copy=False)),
                                 shape=(self.nrows, self.nrows))
        upper = sp.csr_matrix((self.pa, self.pcolidx, self.prowptr),
                              shape=(self.nrows, self.nrows))
        strict = sp.triu(upper, k=1)
        full = (upper + strict.T).tocsr()
        if epsilon:
            full = (full + epsilon * sp.eye(self.nrows, format="csr")).tocsr()
        full.sort_indices()
        return full

    def to_full_coo(self, epsilon: float = 0.0):
        """Full-storage COO triplets (rowidx, colidx, vals), row-major sorted."""
        full = self.to_csr(epsilon).tocoo()
        return (full.row.astype(IDX_DTYPE), full.col.astype(IDX_DTYPE),
                full.data)

    def dsymv(self, x: np.ndarray, epsilon: float = 0.0) -> np.ndarray:
        """y = (A + eps I) x on host (the role of ``acgsymcsrmatrix_dsymv``)."""
        return self.to_csr(epsilon) @ x

    def row_nnz_full(self) -> np.ndarray:
        """Per-row nonzero counts of the full symmetric matrix."""
        return np.diff(self.to_csr().indptr)

    def to_mtx(self) -> MtxFile:
        """Packed upper triangle as a symmetric MtxFile (lower on disk)."""
        # Matrix Market symmetric files conventionally store the lower
        # triangle; transpose our upper storage when writing.
        rows = np.repeat(np.arange(self.nrows, dtype=IDX_DTYPE),
                         np.diff(self.prowptr))
        return MtxFile(object="matrix", format="coordinate", field="real",
                       symmetry="symmetric", nrows=self.nrows,
                       ncols=self.nrows, nnz=self.pnnz,
                       rowidx=self.pcolidx.copy(), colidx=rows,
                       vals=self.pa.copy())
