"""Host-platform (virtual CPU) mesh provisioning.

The reference tests its distributed paths by launching the same binary at
np=1,2,4,8 on one node (SURVEY.md section 4); the TPU build's analog is
XLA's host-platform device simulation.  Getting an n-device virtual CPU
mesh needs a two-step dance that several entry points share (tests,
``__graft_entry__.dryrun_multichip``):

  1. ``--xla_force_host_platform_device_count=n`` in ``XLA_FLAGS``, and
  2. ``jax.config.update("jax_platforms", "cpu")`` -- the env var
     ``JAX_PLATFORMS`` alone is NOT enough because platform plugins (e.g.
     the axon TPU tunnel) override it,

both BEFORE the first JAX backend query: XLA_FLAGS and the platform list
are read once at backend creation and ignored afterwards.
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def host_device_count_flags(flags: str, n_devices: int) -> str:
    """Return ``flags`` amended to request >= ``n_devices`` host devices.

    Pure function: callers decide where the result goes (``os.environ`` of
    this process, or a child-process environment).
    """
    m = re.search(_COUNT_FLAG + r"=(\d+)", flags)
    if m is None:
        return (flags + f" {_COUNT_FLAG}={n_devices}").strip()
    if int(m.group(1)) < n_devices:
        return flags.replace(m.group(0), f"{_COUNT_FLAG}={n_devices}")
    return flags


def provision_host_mesh(n_devices: int):
    """Force the CPU platform with >= ``n_devices`` virtual devices.

    Returns the ``jax`` module.  Must run before the first backend query;
    afterwards the settings are frozen and this becomes a no-op (callers
    should check ``len(jax.devices())``).
    """
    os.environ["XLA_FLAGS"] = host_device_count_flags(
        os.environ.get("XLA_FLAGS", ""), n_devices)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    return jax


def enable_compile_cache(path: str | None = None) -> None:
    """Turn on JAX's persistent compilation cache (client-side).

    Remote/tunneled TPU setups route compiles through a shared service
    whose latency swings with load (observed: trivial programs taking
    9s+, whole-solve compiles stalling for minutes); cached executables
    make repeat runs immune.  Semantics-neutral, on by default for the
    CLI and bench; disable with ``ACG_TPU_COMPILE_CACHE=0``.
    """
    if os.environ.get("ACG_TPU_COMPILE_CACHE", "1") == "0":
        return
    import jax

    if path is None:
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001 -- an optimisation, never fatal
        pass
