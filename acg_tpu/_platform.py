"""Host-platform (virtual CPU) mesh provisioning.

The reference tests its distributed paths by launching the same binary at
np=1,2,4,8 on one node (SURVEY.md section 4); the TPU build's analog is
XLA's host-platform device simulation.  Getting an n-device virtual CPU
mesh needs a two-step dance that several entry points share (tests,
``__graft_entry__.dryrun_multichip``):

  1. ``--xla_force_host_platform_device_count=n`` in ``XLA_FLAGS``, and
  2. ``jax.config.update("jax_platforms", "cpu")`` -- the env var
     ``JAX_PLATFORMS`` alone is NOT enough because platform plugins (e.g.
     the axon TPU tunnel) override it,

both BEFORE the first JAX backend query: XLA_FLAGS and the platform list
are read once at backend creation and ignored afterwards.
"""

from __future__ import annotations

import os
import re
import sys

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def shard_map(f, mesh, in_specs, out_specs):
    """``jax.shard_map`` across jax versions: the top-level binding
    (``check_vma``) only exists on newer runtimes; older ones ship it as
    ``jax.experimental.shard_map.shard_map`` (``check_rep``).  Replicated-
    output checking is disabled either way -- the solve programs return
    psum'd scalars whose replication the checker cannot always prove."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def distributed_initialized() -> bool:
    """Whether ``jax.distributed.initialize`` already ran in this
    process, across jax versions (``is_initialized`` is missing on older
    runtimes; fall back to the internal client handle)."""
    import jax

    if hasattr(jax.distributed, "is_initialized"):
        return bool(jax.distributed.is_initialized())
    try:
        from jax._src.distributed import global_state

        return global_state.client is not None
    except Exception:  # noqa: BLE001 -- conservative: let initialize raise
        return False


def host_device_count_flags(flags: str, n_devices: int) -> str:
    """Return ``flags`` amended to request >= ``n_devices`` host devices.

    Pure function: callers decide where the result goes (``os.environ`` of
    this process, or a child-process environment).
    """
    m = re.search(_COUNT_FLAG + r"=(\d+)", flags)
    if m is None:
        return (flags + f" {_COUNT_FLAG}={n_devices}").strip()
    if int(m.group(1)) < n_devices:
        return flags.replace(m.group(0), f"{_COUNT_FLAG}={n_devices}")
    return flags


def honour_jax_platforms() -> None:
    """Re-apply the ``JAX_PLATFORMS`` env var through ``jax.config``.

    Platform plugins (the axon TPU tunnel) override the env var at
    import time, so a subprocess launched with ``JAX_PLATFORMS=cpu``
    still initialises the tunneled backend -- and HANGS for minutes
    when the tunnel is down.  Call before the first backend query
    (no-op when the var is unset)."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)


def provision_host_mesh(n_devices: int):
    """Force the CPU platform with >= ``n_devices`` virtual devices.

    Returns the ``jax`` module.  Must run before the first backend query;
    afterwards the settings are frozen and this becomes a no-op (callers
    should check ``len(jax.devices())``).
    """
    os.environ["XLA_FLAGS"] = host_device_count_flags(
        os.environ.get("XLA_FLAGS", ""), n_devices)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    return jax


def enable_compile_cache(path: str | None = None) -> None:
    """Turn on JAX's persistent compilation cache (client-side).

    Remote/tunneled TPU setups route compiles through a shared service
    whose latency swings with load (observed: trivial programs taking
    9s+, whole-solve compiles stalling for minutes); cached executables
    make repeat runs immune.  Semantics-neutral, on by default for the
    CLI and bench; disable with ``ACG_TPU_COMPILE_CACHE=0``.
    """
    if os.environ.get("ACG_TPU_COMPILE_CACHE", "1") == "0":
        return
    import jax

    if path is None:
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001 -- an optimisation, never fatal
        pass


# -- bounded backend liveness probe ------------------------------------
#
# The tunneled TPU plugin's backend init has been observed to hang for
# minutes (round 5: a bare ``jax.devices()`` wedged ``dryrun_multichip``
# >90 s with the tunnel down, and bench runs ate ~15 minutes before
# raising UNAVAILABLE).  A signal alarm cannot interrupt the stuck
# C-level init in-process, so the probe runs ``jax.devices()`` in a
# CHILD process under a hard timeout -- the parent learns backend
# liveness without ever risking its own wedge.  Lifted from bench.py
# (round 5) so every entry point (bench, CLI, dryrun) shares one probe.

_probe_cache: tuple[bool, str] | None = None


def _accelerator_plugin_present() -> bool:
    """Whether any PJRT accelerator plugin is importable -- only plugin
    inits (the tunneled TPU one in particular) can hang; a plugin-free
    CPU install has nothing worth probing.  Conservative: unknown means
    True (a missed probe risks a multi-minute wedge, a spurious one
    costs seconds)."""
    import importlib.util

    try:
        if (importlib.util.find_spec("libtpu") is not None
                or importlib.util.find_spec("jax_plugins") is not None):
            return True
        import importlib.metadata as md

        eps = md.entry_points()
        try:
            group = eps.select(group="jax_plugins")
        except AttributeError:          # pre-3.10 dict-style API
            group = eps.get("jax_plugins", [])
        return bool(list(group))
    except Exception:  # noqa: BLE001 -- cannot enumerate: assume present
        return True


def backend_probe_needed() -> bool:
    """Whether a bounded liveness probe is worth its child-process cost.

    Skipped when: the operator opted out (``ACG_TPU_SKIP_BACKEND_PROBE``),
    the requested platform is plain CPU (in-process init cannot hang),
    no accelerator plugin is importable (nothing to hang), or this
    process already created a backend (``jax.devices()`` would return
    instantly either way)."""
    if os.environ.get("ACG_TPU_SKIP_BACKEND_PROBE"):
        return False
    plat = os.environ.get("JAX_PLATFORMS", "")
    names = [p.strip() for p in plat.split(",") if p.strip()]
    if names and all(n == "cpu" for n in names):
        return False
    if not _accelerator_plugin_present():
        return False
    if "jax" in sys.modules:
        try:
            from jax._src import xla_bridge

            if getattr(xla_bridge, "_backends", None):
                return False
        except Exception:  # noqa: BLE001 -- internal API; probe anyway
            pass
    return True


def probe_timeout() -> float:
    """The probe's wait bound (seconds); ``ACG_TPU_PROBE_TIMEOUT``
    overrides the 240 s default (sized to the tunneled backend's slow
    but *live* inits, minutes under shared-service load)."""
    return float(os.environ.get("ACG_TPU_PROBE_TIMEOUT", "240"))


def probe_backend(timeout: float | None = None) -> tuple[bool, str]:
    """Bounded child-process backend liveness probe.

    Returns ``(ok, detail)``: ``ok`` means a child process completed a
    full backend init (``jax.devices()``) within ``timeout`` seconds.
    The child honours ``JAX_PLATFORMS`` (CPU debug runs probe CPU) and
    the fault injector's ``backend:hang`` site (acg_tpu.faults), so
    tunnel-down behaviour is testable without a tunnel.  Results are
    cached for the process lifetime -- backend liveness is decided once.

    ``ACG_TPU_SKIP_BACKEND_PROBE=1`` skips the probe entirely (drivers
    that just proved the backend alive themselves)."""
    global _probe_cache
    if os.environ.get("ACG_TPU_SKIP_BACKEND_PROBE"):
        return True, "probe skipped (ACG_TPU_SKIP_BACKEND_PROBE)"
    if _probe_cache is not None:
        return _probe_cache
    import subprocess

    if timeout is None:
        timeout = probe_timeout()
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    code = ("import acg_tpu.faults as _f; _f.maybe_hang_backend(); "
            "from acg_tpu._platform import honour_jax_platforms; "
            "honour_jax_platforms(); "
            "import jax; jax.devices(); print('ok')")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        _probe_cache = (False, f"backend init exceeded {timeout:.0f}s "
                               f"(tunnel down?)")
        return _probe_cache
    if proc.stdout.strip().endswith("ok"):
        _probe_cache = (True, "ok")
    else:
        tail = (proc.stderr or "").strip().splitlines()
        _probe_cache = (False, f"backend init failed (rc="
                               f"{proc.returncode})"
                               + (f": {tail[-1]}" if tail else ""))
    return _probe_cache


_block_broken: bool | None = None


def block_until_ready_works() -> bool:
    """Whether ``Array.block_until_ready`` actually waits on this
    backend.

    The tunneled TPU plugin has been observed (2026-07-30) to return
    from ``block_until_ready`` in ~0.03 ms while the submitted program
    still runs for seconds -- which silently zeroes every wall-clock
    measurement in the solvers and the bandwidth probe.  Probe once: a
    data-dependent chained program sized to take >= tens of ms must not
    "complete" instantly.  Cached for the process lifetime.
    """
    global _block_broken
    if _block_broken is not None:
        return not _block_broken
    import functools
    import time

    import jax
    import jax.numpy as jnp

    if jax.devices()[0].platform == "cpu":
        # in-process backend: block_until_ready is honest by
        # construction; skip the probe (it would tax every CPU test
        # subprocess with a one-time 256 MB chain run)
        _block_broken = False
        return True

    n = 1 << 26  # 256 MB f32 working vector

    @functools.partial(jax.jit, static_argnames="k")
    def chain(a, k):
        return jax.lax.fori_loop(
            0, k, lambda _, v: jnp.float32(1.0000001) * v + 0.5, a)

    a = jnp.ones((n,), jnp.float32)
    # Grow the chained program until EITHER side of the discriminator is
    # unambiguous.  An honest block absorbs the (k-proportional)
    # execution, leaving the fetch one dispatch round-trip; a broken
    # block returns instantly and pushes the execution into the fetch.
    # Declaring HONEST requires positive evidence (block both long in
    # absolute terms and >= the fetch) because a false "honest" silently
    # zeroes every timing, while a false "broken" merely adds one
    # harmless fetch per measurement -- so the fallthrough is "broken".
    verdict = True  # broken unless proven otherwise
    k = 8
    while k <= 2048:  # 2048 * 0.75 GB: >= 100 ms even at v5p bandwidth
        r = chain(a, k)
        t0 = time.perf_counter()
        r.block_until_ready()
        t_block = time.perf_counter() - t0
        t0 = time.perf_counter()
        jax.device_get(jnp.ravel(r)[:1])  # cannot return before r exists
        t_fetch = time.perf_counter() - t0
        if t_block >= 0.05 and t_block >= t_fetch:
            verdict = False  # block demonstrably waited on real work
            break
        if t_fetch >= 0.25 and t_block * 20 < t_fetch:
            break  # execution landed in the fetch: broken
        k *= 4
    _block_broken = verdict
    if _block_broken:
        import sys
        print("# acg-tpu: block_until_ready does not wait on this "
              "backend; timing falls back to scalar-fetch sync",
              file=sys.stderr)
    return not _block_broken


def device_sync(x) -> None:
    """Wait until ``x`` has actually been computed, even on backends
    whose ``block_until_ready`` lies (see
    :func:`block_until_ready_works`).  The fallback fetches ONE element
    through a dependent slice -- adding a dispatch round-trip, which
    callers doing fine timing should cancel with a chained two-point
    protocol (bench.bandwidth_probe_gbs does)."""
    x.block_until_ready()
    if not block_until_ready_works():
        import jax
        import jax.numpy as jnp

        if getattr(x, "is_fully_addressable", True):
            jax.device_get(jnp.ravel(x)[:1])
        else:
            # multi-controller sharded array: a global [:1] slice is
            # not fetchable from processes that do not own shard 0;
            # sync on one LOCAL shard instead (same completion point --
            # the program finishes as a unit)
            sh = x.addressable_shards[0].data
            jax.device_get(jnp.ravel(sh)[:1])
