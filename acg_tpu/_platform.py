"""Host-platform (virtual CPU) mesh provisioning.

The reference tests its distributed paths by launching the same binary at
np=1,2,4,8 on one node (SURVEY.md section 4); the TPU build's analog is
XLA's host-platform device simulation.  Getting an n-device virtual CPU
mesh needs a two-step dance that several entry points share (tests,
``__graft_entry__.dryrun_multichip``):

  1. ``--xla_force_host_platform_device_count=n`` in ``XLA_FLAGS``, and
  2. ``jax.config.update("jax_platforms", "cpu")`` -- the env var
     ``JAX_PLATFORMS`` alone is NOT enough because platform plugins (e.g.
     the axon TPU tunnel) override it,

both BEFORE the first JAX backend query: XLA_FLAGS and the platform list
are read once at backend creation and ignored afterwards.
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def host_device_count_flags(flags: str, n_devices: int) -> str:
    """Return ``flags`` amended to request >= ``n_devices`` host devices.

    Pure function: callers decide where the result goes (``os.environ`` of
    this process, or a child-process environment).
    """
    m = re.search(_COUNT_FLAG + r"=(\d+)", flags)
    if m is None:
        return (flags + f" {_COUNT_FLAG}={n_devices}").strip()
    if int(m.group(1)) < n_devices:
        return flags.replace(m.group(0), f"{_COUNT_FLAG}={n_devices}")
    return flags


def honour_jax_platforms() -> None:
    """Re-apply the ``JAX_PLATFORMS`` env var through ``jax.config``.

    Platform plugins (the axon TPU tunnel) override the env var at
    import time, so a subprocess launched with ``JAX_PLATFORMS=cpu``
    still initialises the tunneled backend -- and HANGS for minutes
    when the tunnel is down.  Call before the first backend query
    (no-op when the var is unset)."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)


def provision_host_mesh(n_devices: int):
    """Force the CPU platform with >= ``n_devices`` virtual devices.

    Returns the ``jax`` module.  Must run before the first backend query;
    afterwards the settings are frozen and this becomes a no-op (callers
    should check ``len(jax.devices())``).
    """
    os.environ["XLA_FLAGS"] = host_device_count_flags(
        os.environ.get("XLA_FLAGS", ""), n_devices)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    return jax


def enable_compile_cache(path: str | None = None) -> None:
    """Turn on JAX's persistent compilation cache (client-side).

    Remote/tunneled TPU setups route compiles through a shared service
    whose latency swings with load (observed: trivial programs taking
    9s+, whole-solve compiles stalling for minutes); cached executables
    make repeat runs immune.  Semantics-neutral, on by default for the
    CLI and bench; disable with ``ACG_TPU_COMPILE_CACHE=0``.
    """
    if os.environ.get("ACG_TPU_COMPILE_CACHE", "1") == "0":
        return
    import jax

    if path is None:
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001 -- an optimisation, never fatal
        pass


_block_broken: bool | None = None


def block_until_ready_works() -> bool:
    """Whether ``Array.block_until_ready`` actually waits on this
    backend.

    The tunneled TPU plugin has been observed (2026-07-30) to return
    from ``block_until_ready`` in ~0.03 ms while the submitted program
    still runs for seconds -- which silently zeroes every wall-clock
    measurement in the solvers and the bandwidth probe.  Probe once: a
    data-dependent chained program sized to take >= tens of ms must not
    "complete" instantly.  Cached for the process lifetime.
    """
    global _block_broken
    if _block_broken is not None:
        return not _block_broken
    import functools
    import time

    import jax
    import jax.numpy as jnp

    if jax.devices()[0].platform == "cpu":
        # in-process backend: block_until_ready is honest by
        # construction; skip the probe (it would tax every CPU test
        # subprocess with a one-time 256 MB chain run)
        _block_broken = False
        return True

    n = 1 << 26  # 256 MB f32 working vector

    @functools.partial(jax.jit, static_argnames="k")
    def chain(a, k):
        return jax.lax.fori_loop(
            0, k, lambda _, v: jnp.float32(1.0000001) * v + 0.5, a)

    a = jnp.ones((n,), jnp.float32)
    # Grow the chained program until EITHER side of the discriminator is
    # unambiguous.  An honest block absorbs the (k-proportional)
    # execution, leaving the fetch one dispatch round-trip; a broken
    # block returns instantly and pushes the execution into the fetch.
    # Declaring HONEST requires positive evidence (block both long in
    # absolute terms and >= the fetch) because a false "honest" silently
    # zeroes every timing, while a false "broken" merely adds one
    # harmless fetch per measurement -- so the fallthrough is "broken".
    verdict = True  # broken unless proven otherwise
    k = 8
    while k <= 2048:  # 2048 * 0.75 GB: >= 100 ms even at v5p bandwidth
        r = chain(a, k)
        t0 = time.perf_counter()
        r.block_until_ready()
        t_block = time.perf_counter() - t0
        t0 = time.perf_counter()
        jax.device_get(jnp.ravel(r)[:1])  # cannot return before r exists
        t_fetch = time.perf_counter() - t0
        if t_block >= 0.05 and t_block >= t_fetch:
            verdict = False  # block demonstrably waited on real work
            break
        if t_fetch >= 0.25 and t_block * 20 < t_fetch:
            break  # execution landed in the fetch: broken
        k *= 4
    _block_broken = verdict
    if _block_broken:
        import sys
        print("# acg-tpu: block_until_ready does not wait on this "
              "backend; timing falls back to scalar-fetch sync",
              file=sys.stderr)
    return not _block_broken


def device_sync(x) -> None:
    """Wait until ``x`` has actually been computed, even on backends
    whose ``block_until_ready`` lies (see
    :func:`block_until_ready_works`).  The fallback fetches ONE element
    through a dependent slice -- adding a dispatch round-trip, which
    callers doing fine timing should cancel with a chained two-point
    protocol (bench.bandwidth_probe_gbs does)."""
    x.block_until_ready()
    if not block_until_ready_works():
        import jax
        import jax.numpy as jnp

        if getattr(x, "is_fully_addressable", True):
            jax.device_get(jnp.ravel(x)[:1])
        else:
            # multi-controller sharded array: a global [:1] slice is
            # not fetchable from processes that do not own shard 0;
            # sync on one LOCAL shard instead (same completion point --
            # the program finishes as a unit)
            sh = x.addressable_shards[0].data
            jax.device_get(jnp.ravel(sh)[:1])
