"""Survivor-mesh supervisor + chaos campaign -- the elastic half of
the recovery ladder.

The in-process ladder (acg_tpu.solvers.resilience: rollback -> restart
-> fallback -> agreed abort) ends where the PROCESS ends: a crash, a
lost peer, or an exhausted restart budget leaves a committed snapshot
on disk and nothing to consume it.  The reference's answer is the
erragree convention -- all ranks agree, then abort (PAPER.md) -- which
turns one dead chip into a dead pod slice until an operator restores
full capacity.  This module closes the loop from the HOST side:

* :func:`supervise` (CLI ``--supervise``) launches the solve as a
  child process and watches the EXIT-CODE CONTRACT
  (:data:`acg_tpu.errors.EXIT_CONTRACT`, rendered by ``--buildinfo``):
  a ``crash:exit`` death (rc 94), an erragree heartbeat/watchdog
  teardown (rc 97), an injected dead peer (rc 86), a signal death or a
  failed solve relaunches the child with ``--resume`` from the last
  committed snapshot, under a bounded relaunch budget with exponential
  backoff.  When the failure means a LOST PEER (``--shrink
  peer-lost``, the default; ``--shrink any`` lets a single-host crash
  demonstrate the same ladder), the relaunch SHRINKS ``--nparts`` onto
  the survivor mesh and adds ``--resume-repartition`` -- the
  shape-portable snapshot (acg_tpu.checkpoint.reassemble_global) makes
  the N-part carry restore onto M parts and continue to the ORIGINAL
  tolerance.  Drift (rc 7) and SLO (rc 8) verdicts describe COMPLETED
  runs and pass through.  Every relaunch decision lands on the
  existing planes: ``acg_recovery_relaunches_total`` /
  ``acg_recovery_mttr_seconds`` metric families, a ``recovery:`` stats
  section on stderr, a recovery document in the ``--history`` ledger,
  and the relaunched child's status document carries a ``degraded:
  {from, to, reason}`` key (via :data:`acg_tpu.observatory.DEGRADED_ENV`).

* :func:`run_chaos` (CLI ``--chaos SEED[:N]``) PROVES the ladder
  instead of asserting it: N seeded randomized schedules over the
  existing fault sites (``crash:exit``, ``sdc:flip`` when ``--abft``
  is armed, spmv/halo/dot corruption, ``peer:dead`` under
  ``--multihost``, ``solve:slow`` under ``--soak``) each run through
  the supervisor, and every GREEN run is independently verified: the
  solution is re-read from disk and its true relative residual checked
  against a host-side rebuild of the matrix.  Per-schedule verdicts --
  converged / agreed-abort / WRONG-ANSWER -- land on stderr and in the
  ``--history`` ledger (``acg-tpu-chaos/1`` documents); the campaign
  exits :data:`~acg_tpu.errors.ExitCode.WRONG_ANSWER` (96) if ANY
  schedule converged to a wrong answer.  The acceptance bar is zero
  wrong-answer-green.

The supervisor is pure host-side process management: it never imports
jax, so a wedged backend cannot wedge the supervisor, and the compiled
solve programs are untouched by construction.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

import numpy as np

from acg_tpu.errors import (ExitCode, PEER_LOST_CODES,
                            RELAUNCHABLE_CODES)

# flags the supervisor consumes (flag -> number of value tokens);
# never forwarded to the child.  --metrics-file belongs to the
# SUPERVISOR in supervise mode: each child's registry dies with it,
# while the supervisor's carries the acg_recovery_* families across
# relaunches.
SUPERVISOR_FLAGS = {
    "--supervise": 0,
    "--relaunch-budget": 1,
    "--relaunch-backoff": 1,
    "--shrink": 1,
    "--min-parts": 1,
    "--grow-after": 1,
    "--chaos": 1,
    "--metrics-file": 1,
}

# bound on one child solve; generous next to the tier-1 budget but
# finite -- a wedged child must become a relaunchable failure, not a
# wedged supervisor
CHILD_TIMEOUT_SECS = 900.0


# -- argv surgery ----------------------------------------------------------

def strip_flags(argv: list, flags: dict) -> list:
    """``argv`` without the named flags (and their value tokens;
    ``--flag=value`` forms too)."""
    out = []
    i = 0
    while i < len(argv):
        tok = argv[i]
        name = tok.split("=", 1)[0]
        if name in flags:
            i += 1 + (flags[name] if "=" not in tok else 0)
            continue
        out.append(tok)
        i += 1
    return out


def flag_value(argv: list, flag: str):
    """The LAST value of ``--flag V`` / ``--flag=V`` in argv, or
    None."""
    val = None
    for i, tok in enumerate(argv):
        if tok == flag and i + 1 < len(argv):
            val = argv[i + 1]
        elif tok.startswith(flag + "="):
            val = tok.split("=", 1)[1]
    return val


def set_flag(argv: list, flag: str, value) -> list:
    """argv with ``--flag value`` replaced (or appended)."""
    out = strip_flags(argv, {flag: 1})
    return out + ([flag] if value is None else [flag, str(value)])


def _fault_site(argv: list, env: dict) -> str | None:
    """The armed fault spec's SITE (argv ``--fault-inject`` or the
    inherited env var), or None."""
    spec = flag_value(argv, "--fault-inject") \
        or env.get("ACG_TPU_FAULT_INJECT")
    return spec.split(":", 1)[0] if spec else None


def _strip_fault(argv: list, env: dict) -> tuple:
    """The relaunch's fault hygiene: injected faults model TRANSIENT
    events whose damage is already done -- re-arming one in the
    relaunched child would deterministically re-break the very run
    that exists to survive it.  The one exception is ``crash:exit``,
    whose crossing semantics (faults.maybe_crash) make it provably
    re-fire-safe on resume; keeping it armed tests exactly that."""
    if _fault_site(argv, env) == "crash":
        return argv, env
    env = {k: v for k, v in env.items() if k != "ACG_TPU_FAULT_INJECT"}
    return strip_flags(argv, {"--fault-inject": 1}), env


def _reason(rc: int) -> str:
    if rc in PEER_LOST_CODES:
        return "peer-lost"
    if rc == int(ExitCode.CRASH_INJECTED):
        return "crash"
    if rc < 0:
        return "signal"
    if rc == int(ExitCode.BACKEND_UNAVAILABLE):
        return "backend"
    return "failure"


# -- the supervisor core ---------------------------------------------------

def supervise(child_argv: list, *, ckpt_path: str, budget: int = 3,
              backoff: float = 1.0, shrink: str = "peer-lost",
              min_parts: int = 1, nparts: int = 0, env: dict | None = None,
              capture: bool = False, label: str = "",
              timeout: float = CHILD_TIMEOUT_SECS) -> dict:
    """Run ``python -m acg_tpu.cli <child_argv>`` under the relaunch
    policy; returns the report dict the ``recovery:`` section and the
    chaos ledger render:

    ``{"rc", "attempts", "relaunches": [{"rc", "reason", "parts"}...],
    "degraded": {"from", "to", "reason"} | None, "mttr_seconds",
    "outcome"}``

    ``nparts`` is the launch partition count (0 = unknown: shrink
    disabled); ``capture`` collects child stdout/stderr into the
    report (the chaos driver) instead of inheriting the terminal (the
    interactive ``--supervise`` mode)."""
    from acg_tpu import metrics

    child_env = dict(os.environ if env is None else env)
    argv = list(child_argv)
    cur_parts = int(nparts or 0)
    tag = f"supervisor{f' [{label}]' if label else ''}"
    report: dict = {"rc": None, "attempts": 0, "relaunches": [],
                    "degraded": None, "mttr_seconds": None}
    first_failure = None
    attempt = 0
    while True:
        attempt += 1
        report["attempts"] = attempt
        cmd = [sys.executable, "-m", "acg_tpu.cli", *argv]
        try:
            proc = subprocess.run(
                cmd, env=child_env, timeout=timeout,
                capture_output=capture, text=capture)
            rc = int(proc.returncode)
            if capture:
                report["stderr_tail"] = (proc.stderr or "")[-4000:]
                report["stdout_tail"] = (proc.stdout or "")[-1000:]
        except subprocess.TimeoutExpired:
            rc = -1
            sys.stderr.write(f"acg-tpu: {tag}: child timed out after "
                             f"{timeout:.0f}s; treating as a crash\n")
        if rc == 0:
            if first_failure is not None:
                mttr = time.monotonic() - first_failure
                report["mttr_seconds"] = round(mttr, 3)
                metrics.record_recovery_mttr(mttr)
            report["rc"] = 0
            report["outcome"] = "converged"
            return report
        if rc in (int(ExitCode.DRIFT), int(ExitCode.SLO_BREACH)):
            # the solve COMPLETED; the service-level gate tripped --
            # a relaunch would re-run a finished solve
            sys.stderr.write(f"acg-tpu: {tag}: child exited rc {rc} "
                             f"({_reason(rc)} gate on a completed "
                             f"run); passing through\n")
            report["rc"] = rc
            report["outcome"] = "gate"
            return report
        reason = _reason(rc)
        if first_failure is None:
            first_failure = time.monotonic()
        relaunchable = (rc in RELAUNCHABLE_CODES or rc < 0)
        have_snap = os.path.exists(ckpt_path)
        if not relaunchable or not have_snap \
                or len(report["relaunches"]) >= max(int(budget), 0):
            why = ("relaunch budget exhausted" if relaunchable
                   and have_snap else
                   "no snapshot to resume from" if relaunchable
                   else "not a relaunchable failure")
            sys.stderr.write(f"acg-tpu: {tag}: child exited rc {rc} "
                             f"({reason}); {why} -- giving up\n")
            report["rc"] = (int(ExitCode.RELAUNCH_BUDGET)
                           if relaunchable and have_snap else rc)
            report["outcome"] = "agreed-abort"
            return report

        # -- relaunch with --resume (and maybe a shrunken mesh) --------
        nrel = len(report["relaunches"]) + 1
        do_shrink = (shrink != "never"
                     and (reason == "peer-lost" or shrink == "any")
                     and cur_parts > max(int(min_parts), 1))
        argv, child_env = _strip_fault(argv, child_env)
        argv = set_flag(argv, "--resume", ckpt_path)
        mesh_note = ""
        if do_shrink:
            new_parts = max(max(int(min_parts), 1), cur_parts // 2)
            mesh_note = f", shrinking {cur_parts} -> {new_parts} parts"
            argv = set_flag(argv, "--nparts", new_parts)
            if "--resume-repartition" not in argv:
                argv.append("--resume-repartition")
            frm = report["degraded"]["from"] if report["degraded"] \
                else cur_parts
            report["degraded"] = {"from": int(frm), "to": int(new_parts),
                                  "reason": reason}
            from acg_tpu.observatory import DEGRADED_ENV
            child_env[DEGRADED_ENV] = f"{frm}:{new_parts}:{reason}"
            cur_parts = new_parts
        sleep = max(float(backoff), 0.0) * (2 ** (nrel - 1))
        sys.stderr.write(
            f"acg-tpu: {tag}: child exited rc {rc} ({reason}); "
            f"relaunch {nrel}/{int(budget)} with --resume"
            f"{mesh_note}"
            f"{f' after {sleep:.1f}s backoff' if sleep else ''}\n")
        report["relaunches"].append(
            {"rc": rc, "reason": reason, "parts": cur_parts})
        metrics.record_relaunch(reason)
        if sleep:
            time.sleep(sleep)


def _recovery_section(report: dict) -> str:
    """The ``recovery:`` stats section (stderr; the stats-block
    convention)."""
    lines = ["recovery:"]
    lines.append(f"  attempts: {report['attempts']}")
    rel = report["relaunches"]
    by = {}
    for r in rel:
        by[r["reason"]] = by.get(r["reason"], 0) + 1
    detail = (" (" + ", ".join(f"{k}: {v}"
                               for k, v in sorted(by.items())) + ")"
              if by else "")
    lines.append(f"  relaunches: {len(rel)}{detail}")
    if report.get("degraded"):
        d = report["degraded"]
        lines.append(f"  degraded: {d['from']} -> {d['to']} parts "
                     f"({d['reason']})")
    if report.get("mttr_seconds") is not None:
        lines.append(f"  mttr seconds: {report['mttr_seconds']:.3f}")
    lines.append(f"  outcome: {report.get('outcome')} "
                 f"(rc {report.get('rc')})")
    return "\n".join(lines) + "\n"


def _history_recovery_doc(args, report: dict, kind: str = "recovery",
                          extra: dict | None = None) -> dict:
    """A ledger document for one supervised incident/schedule --
    index-compatible with observatory.history_append."""
    doc = {
        "schema": f"acg-tpu-{kind}/1",
        "manifest": {"matrix": str(args.A), "solver": args.solver,
                     "nparts": int(args.nparts or 0),
                     "dtype": args.dtype,
                     "unix_time": time.time()},
        "stats": {"converged": report.get("rc") == 0},
        "recovery": {k: report.get(k) for k in
                     ("rc", "attempts", "relaunches", "degraded",
                      "mttr_seconds", "outcome")},
    }
    if extra:
        doc.update(extra)
    return doc


def _supervise_validate(args) -> None:
    if args.ckpt is None or (args.ckpt_every <= 0
                             and args.ckpt_secs <= 0):
        raise SystemExit(
            "acg-tpu: --supervise/--chaos relaunch from committed "
            "snapshots; arm --ckpt FILE with --ckpt-every K or "
            "--ckpt-secs S")
    if args.resume is not None:
        raise SystemExit(
            "acg-tpu: --supervise owns the --resume injection on "
            "relaunch; start it without --resume")
    if args.explain:
        raise SystemExit("acg-tpu: --supervise runs solves, not "
                         "--explain analysis passes")
    if args.relaunch_budget < 0:
        raise SystemExit("acg-tpu: --relaunch-budget must be >= 0")
    if args.relaunch_backoff < 0:
        raise SystemExit("acg-tpu: --relaunch-backoff must be >= 0 "
                         "seconds")
    if args.min_parts < 1:
        raise SystemExit("acg-tpu: --min-parts must be >= 1")


def run_supervised(args, argv: list) -> int:
    """The ``--supervise`` CLI mode."""
    from acg_tpu import metrics

    _supervise_validate(args)
    child_argv = strip_flags(argv, SUPERVISOR_FLAGS)
    metrics.arm()
    report = supervise(
        child_argv, ckpt_path=args.ckpt,
        budget=args.relaunch_budget, backoff=args.relaunch_backoff,
        shrink=args.shrink, min_parts=args.min_parts,
        nparts=int(args.nparts or 0))
    sys.stderr.write(_recovery_section(report))
    if args.history:
        from acg_tpu import observatory
        try:
            observatory.history_append(
                args.history, _history_recovery_doc(args, report))
        except OSError as e:
            sys.stderr.write(f"acg-tpu: --history {args.history}: "
                             f"{e}\n")
    if args.metrics_file:
        try:
            metrics.write_textfile(args.metrics_file)
        except OSError as e:
            sys.stderr.write(f"acg-tpu: --metrics-file "
                             f"{args.metrics_file}: {e}\n")
    metrics.disarm()
    return int(report["rc"])


# -- the daemon supervisor (--serve --supervise) ---------------------------

class DaemonSupervisor:
    """The relaunch loop for a LONG-LIVED child (the ``--serve``
    daemon).  :func:`supervise` models a batch child -- run to
    completion, then judge the exit code; a daemon never completes, so
    this variant runs the child under ``subprocess.Popen`` on a
    watcher thread and applies the same exit-code contract to every
    unexpected death: relaunch within budget (the daemon WARM-RESTORES
    its operator cache from the serve state sidecar -- no ``--resume``
    injection), shrink ``--nparts`` on crash-class deaths, and -- the
    other half of PR 10's one-way ratchet -- GROW back: a shrunken
    child that stays healthy for ``grow_after`` served requests is
    deliberately relaunched toward the original mesh width with
    ``--resume-repartition``, counted by
    ``acg_recovery_regrows_total``."""

    POLL_SECS = 0.2

    def __init__(self, child_argv: list, *, state_path: str,
                 budget: int = 3, backoff: float = 1.0,
                 shrink: str = "any", min_parts: int = 1,
                 nparts: int = 0, grow_after: int = 0,
                 env: dict | None = None, label: str = "serve"):
        import threading
        self.argv = list(child_argv)
        self.state_path = state_path
        self.budget = max(int(budget), 0)
        self.backoff = max(float(backoff), 0.0)
        self.shrink = shrink
        self.min_parts = max(int(min_parts), 1)
        self.orig_parts = int(nparts or 0)
        self.cur_parts = self.orig_parts
        self.grow_after = max(int(grow_after), 0)
        self.env = dict(os.environ if env is None else env)
        self.tag = f"supervisor [{label}]"
        self.report: dict = {"rc": None, "relaunches": [],
                             "regrows": 0, "degraded": None,
                             "outcome": None}
        self._proc: subprocess.Popen | None = None
        self._stop = threading.Event()
        self._served_at_launch = 0
        self._thread = threading.Thread(target=self._loop,
                                        name="acg-daemon-supervisor",
                                        daemon=True)

    # -- state-file probes -------------------------------------------------

    def _served(self) -> int:
        """``requests_served`` from the serve state sidecar (the
        cumulative counter the daemon persists after every request);
        0 when unreadable."""
        import json
        try:
            with open(self.state_path) as f:
                return int(json.load(f).get("requests_served", 0))
        except (OSError, ValueError, TypeError):
            return 0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "DaemonSupervisor":
        self._launch()
        self._thread.start()
        return self

    def stop(self) -> None:
        """Deliberate wind-down: never counted as a failure."""
        self._stop.set()
        proc = self._proc
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=15.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
        if self._thread.is_alive():
            self._thread.join(timeout=10.0)

    def wait(self) -> int:
        """Block until the loop ends (clean child exit, budget
        exhausted, or a non-relaunchable death); the final rc."""
        while self._thread.is_alive():
            self._thread.join(timeout=0.5)
        return int(self.report["rc"] or 0)

    # -- the loop ----------------------------------------------------------

    def _launch(self) -> None:
        self._served_at_launch = self._served()
        cmd = [sys.executable, "-m", "acg_tpu.cli", *self.argv]
        self._proc = subprocess.Popen(cmd, env=self.env)

    def _relaunch(self, *, parts: int | None, reason: str,
                  grow: bool) -> None:
        from acg_tpu import metrics
        mesh_note = ""
        if parts is not None and parts != self.cur_parts:
            mesh_note = (f", {'growing' if grow else 'shrinking'} "
                         f"{self.cur_parts} -> {parts} parts")
            self.argv = set_flag(self.argv, "--nparts", parts)
            if grow:
                if "--resume-repartition" not in self.argv:
                    self.argv.append("--resume-repartition")
                if parts >= self.orig_parts:
                    from acg_tpu.observatory import DEGRADED_ENV
                    self.report["degraded"] = None
                    self.env.pop(DEGRADED_ENV, None)
            else:
                from acg_tpu.observatory import DEGRADED_ENV
                frm = (self.report["degraded"]["from"]
                       if self.report["degraded"] else self.cur_parts)
                self.report["degraded"] = {"from": int(frm),
                                           "to": int(parts),
                                           "reason": reason}
                self.env[DEGRADED_ENV] = f"{frm}:{parts}:{reason}"
            self.cur_parts = parts
        if grow:
            self.report["regrows"] += 1
            metrics.record_regrow()
            sys.stderr.write(f"acg-tpu: {self.tag}: child healthy for "
                             f"{self.grow_after}+ requests -- regrow "
                             f"relaunch{mesh_note}\n")
        else:
            nrel = len(self.report["relaunches"]) + 1
            sleep = self.backoff * (2 ** (nrel - 1))
            sys.stderr.write(f"acg-tpu: {self.tag}: daemon died "
                             f"({reason}); relaunch {nrel}/"
                             f"{self.budget}{mesh_note}"
                             f"{f' after {sleep:.1f}s' if sleep else ''}"
                             "\n")
            self.report["relaunches"].append(
                {"reason": reason, "parts": self.cur_parts})
            metrics.record_relaunch(reason)
            if sleep:
                time.sleep(sleep)
        self._launch()

    def _loop(self) -> None:
        while not self._stop.is_set():
            proc = self._proc
            rc = proc.poll() if proc is not None else None
            if rc is None:
                if (self.grow_after > 0
                        and 0 < self.cur_parts < self.orig_parts
                        and (self._served() - self._served_at_launch
                             >= self.grow_after)):
                    proc.terminate()
                    try:
                        proc.wait(timeout=30.0)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        proc.wait(timeout=5.0)
                    self._relaunch(
                        parts=min(self.orig_parts,
                                  max(self.cur_parts * 2, 1)),
                        reason="regrow", grow=True)
                    continue
                self._stop.wait(self.POLL_SECS)
                continue
            if self._stop.is_set():
                break
            rc = int(rc)
            if rc == 0:
                self.report["rc"] = 0
                self.report["outcome"] = "clean-exit"
                return
            reason = _reason(rc)
            relaunchable = (rc in RELAUNCHABLE_CODES or rc < 0)
            if (not relaunchable
                    or len(self.report["relaunches"]) >= self.budget):
                why = ("relaunch budget exhausted" if relaunchable
                       else "not a relaunchable failure")
                sys.stderr.write(f"acg-tpu: {self.tag}: daemon died "
                                 f"rc {rc} ({reason}); {why} -- "
                                 f"giving up\n")
                self.report["rc"] = (int(ExitCode.RELAUNCH_BUDGET)
                                     if relaunchable else rc)
                self.report["outcome"] = "gave-up"
                return
            parts = None
            if (self.shrink != "never"
                    and (reason == "peer-lost" or self.shrink == "any")
                    and self.cur_parts > self.min_parts):
                parts = max(self.min_parts, self.cur_parts // 2)
            self._relaunch(parts=parts, reason=reason, grow=False)


def supervise_daemon(child_argv: list, *, state_path: str,
                     budget: int = 3, backoff: float = 1.0,
                     shrink: str = "any", min_parts: int = 1,
                     nparts: int = 0, grow_after: int = 0,
                     env: dict | None = None,
                     label: str = "serve") -> DaemonSupervisor:
    """Launch ``python -m acg_tpu.cli <child_argv>`` (a ``--serve``
    daemon) under the relaunch/shrink/grow policy; returns the STARTED
    :class:`DaemonSupervisor` (``.stop()`` to wind down, ``.wait()``
    to block)."""
    return DaemonSupervisor(
        child_argv, state_path=state_path, budget=budget,
        backoff=backoff, shrink=shrink, min_parts=min_parts,
        nparts=nparts, grow_after=grow_after, env=env,
        label=label).start()


def run_supervised_serve(args, argv: list) -> int:
    """The ``--serve --supervise`` CLI mode: the self-healing service.
    Unlike batch ``--supervise`` there is no snapshot-cadence
    requirement -- the daemon persists its serve state after every
    request -- but ``--ckpt`` must be armed so the state has a home."""
    import signal

    from acg_tpu import metrics

    if args.ckpt is None:
        raise SystemExit(
            "acg-tpu: --serve --supervise warm-restores the daemon "
            "from its persisted serve state; arm --ckpt FILE")
    if args.resume is not None:
        raise SystemExit(
            "acg-tpu: --serve --supervise owns relaunches; start it "
            "without --resume")
    metrics.arm()
    child_argv = strip_flags(argv, SUPERVISOR_FLAGS)
    sup = supervise_daemon(
        child_argv, state_path=args.ckpt + ".serve.json",
        budget=args.relaunch_budget, backoff=args.relaunch_backoff,
        shrink=args.shrink, min_parts=args.min_parts,
        nparts=int(args.nparts or 0),
        grow_after=int(getattr(args, "grow_after", 0) or 0))

    def _term(signum, frame):
        sys.stderr.write(f"acg-tpu: supervisor [serve]: signal "
                         f"{signum} -- stopping the daemon\n")
        sup.stop()

    try:
        signal.signal(signal.SIGTERM, _term)
        signal.signal(signal.SIGINT, _term)
    except ValueError:
        pass
    try:
        rc = sup.wait()
    except KeyboardInterrupt:
        sup.stop()
        rc = 0
    rep = dict(sup.report)
    sys.stderr.write(
        "recovery:\n"
        f"  relaunches: {len(rep['relaunches'])}\n"
        f"  regrows: {rep['regrows']}\n"
        f"  outcome: {rep.get('outcome')} (rc {rep.get('rc')})\n")
    if args.metrics_file:
        try:
            metrics.write_textfile(args.metrics_file)
        except OSError as e:
            sys.stderr.write(f"acg-tpu: --metrics-file "
                             f"{args.metrics_file}: {e}\n")
    metrics.disarm()
    return rc


# -- the chaos campaign ----------------------------------------------------

def parse_chaos(spec: str) -> tuple:
    """``SEED[:N]`` -> (seed, nschedules); N defaults to 20 (the
    acceptance campaign's floor)."""
    head, _, tail = str(spec).partition(":")
    try:
        seed = int(head)
        n = int(tail) if tail else 20
        if n <= 0:
            raise ValueError
    except ValueError:
        raise SystemExit(f"acg-tpu: --chaos {spec!r}: expected "
                         f"SEED[:N] with positive N")
    return seed, n


def chaos_schedule(index: int, seed: int, args) -> str | None:
    """Schedule ``index``'s fault spec (None = fault-free control run)
    -- deterministic in (seed, index), drawn over the sites THIS
    configuration can fire: crash:exit needs the (enforced) armed
    checkpoint; sdc:flip is only detectable-and-survivable with
    --abft, so it only enters the menu then (unarmed sdc is the
    known-wrong-answer negative control, proven in
    tests/test_checkpoint.py); halo faults need a mesh; peer faults
    need controllers; solve:slow needs the soak driver's hook."""
    rng = np.random.default_rng([int(seed), int(index)])
    menu = ["none", "crash", "spmv:nan", "spmv:inf", "dot:nan",
            "dot:neg"]
    if int(args.nparts or 0) > 1:
        menu.append("halo:nan")
    if args.abft and int(getattr(args, "audit_every", 0)) > 0:
        menu.append("sdc:flip")
    if args.multihost or args.coordinator is not None:
        menu.append("peer:dead")
    if args.soak:
        menu.append("solve:slow")
    pick = menu[int(rng.integers(len(menu)))]
    if pick == "none":
        return None
    # firing iteration biased LOW (quadratic): the iteration cap is
    # usually far past convergence, and a fault drawn past the last
    # iteration never fires -- a silent extra control run.  Some
    # high draws stay in deliberately: fault-never-fires is a real
    # schedule class too.
    hi = max(int(args.max_iterations * 0.6), 3)
    k = 2 + int((hi - 2) * float(rng.random()) ** 2)
    if pick == "sdc:flip":
        # the ABFT contract: the checksum test verifies the CURRENT
        # SpMV product at the audit cadence ((k+1) % every == 0), so a
        # flip between audits is undetectable BY DESIGN (the documented
        # negative control, tests/test_checkpoint.py) -- campaign
        # schedules land the flip on an audited iteration, where the
        # ladder (detect -> breakdown -> rollback/relaunch) must hold
        ae = max(int(args.audit_every), 1)
        k = max((k // ae) * ae + (ae - 1), ae - 1)
        return f"sdc:flip@{k}:seed={int(rng.integers(1 << 16))}"
    if pick == "crash":
        return f"crash:exit@{k}"
    if pick == "peer:dead":
        nproc = int(getattr(args, "num_processes", None) or 2)
        return f"peer:dead:proc={int(rng.integers(nproc))}"
    if pick == "solve:slow":
        return f"solve:slow@{max(int(args.soak) // 2, 1)}:secs=0.05"
    el = int(rng.integers(1 << 16))
    if pick.startswith("dot:"):
        return f"{pick}@{k}"
    return f"{pick}@{k}:seed={el}"


def _host_system(args):
    """The verification oracle: the matrix rebuilt host-side (via the
    SAME synthesis dispatch the children's CLI uses -- it cannot drift
    from the matrix solved) and the b the children solved against.
    The children's compiled SpMV/solve shares nothing with the scipy
    residual computed here."""
    from acg_tpu.matrix import SymCsrMatrix

    if args.A.startswith("gen:"):
        from acg_tpu.cli import synthesize_host_matrix
        A = synthesize_host_matrix(args.A, aniso=args.aniso,
                                   seed=args.seed)
    else:
        from acg_tpu.io.mtxfile import read_mtx
        A = SymCsrMatrix.from_mtx(read_mtx(args.A, binary=args.binary))
    csr = A.to_csr(epsilon=args.epsilon)
    return csr, np.ones(csr.shape[0])


def verify_solution(csr, b, out_path: str, rtol: float,
                    atol: float = 0.0) -> tuple:
    """``(ok, relative_residual)`` of the solution the child wrote --
    the wrong-answer-green detector.  The margin (x50) covers
    repartition dot-product re-association and the recurrence-vs-true
    residual gap of a HEALTHY run; silent corruption leaves residuals
    orders of magnitude past it."""
    from acg_tpu.io.mtxfile import read_mtx

    x = np.asarray(read_mtx(out_path, binary=True).vals,
                   dtype=np.float64).reshape(-1)
    if x.size != b.size or not np.isfinite(x).all():
        return False, float("inf")
    bn = float(np.linalg.norm(b)) or 1.0
    rel = float(np.linalg.norm(b - csr @ x)) / bn
    bound = max(float(rtol), float(atol) / bn, 1e-14) * 50.0
    return rel <= bound, rel


def verify_solution_dense(csr, b, x, rtol: float,
                          atol: float = 0.0) -> tuple:
    """:func:`verify_solution` for an IN-MEMORY solution vector (the
    chaos-serve campaign reads x off the HTTP response instead of a
    file); same x50 margin, same wrong-answer-green contract."""
    x = np.asarray(x, dtype=np.float64).reshape(-1)
    if x.size != b.size or not np.isfinite(x).all():
        return False, float("inf")
    bn = float(np.linalg.norm(b)) or 1.0
    rel = float(np.linalg.norm(b - csr @ x)) / bn
    bound = max(float(rtol), float(atol) / bn, 1e-14) * 50.0
    return rel <= bound, rel


def run_chaos(args, argv: list) -> int:
    """The ``--chaos SEED[:N]`` campaign driver."""
    import tempfile

    from acg_tpu import metrics

    _supervise_validate(args)
    unsupported = [flag for flag, on in [
        ("--manufactured-solution (chaos verifies against b = ones)",
         args.manufactured_solution),
        ("b/x0 input files", bool(args.b or args.x0)),
        ("--distributed-read", args.distributed_read),
        ("--output-comm-matrix", args.output_comm_matrix),
        ("--fault-inject (the campaign owns the fault schedule)",
         args.fault_inject is not None),
    ] if on]
    if unsupported:
        raise SystemExit(f"acg-tpu: --chaos does not support: "
                         f"{', '.join(unsupported)}")
    seed, nsched = parse_chaos(args.chaos)
    try:
        csr, b = _host_system(args)
    except Exception as e:  # noqa: BLE001 -- refuse, don't crash
        raise SystemExit(
            f"acg-tpu: --chaos cannot build the host verification "
            f"oracle for {args.A}: {e}")
    base_argv = strip_flags(argv, SUPERVISOR_FLAGS)
    metrics.arm()
    tally = {"converged": 0, "agreed-abort": 0, "WRONG-ANSWER": 0}
    worst = []
    tmpdir = tempfile.mkdtemp(prefix="acg-chaos-")
    sys.stderr.write(f"acg-tpu: chaos: {nsched} schedules from seed "
                     f"{seed} over {args.A}\n")
    for i in range(nsched):
        spec = chaos_schedule(i, seed, args)
        out = os.path.join(tmpdir, f"x{i}.mtx")
        argv_i = set_flag(strip_flags(base_argv, {"--output": 1}),
                          "-o", out)
        argv_i = set_flag(argv_i, "--ckpt",
                          os.path.join(tmpdir, f"ck{i}"))
        if "--quiet" not in argv_i and "-q" not in argv_i:
            argv_i.append("--quiet")
        env = dict(os.environ)
        env.pop("ACG_TPU_FAULT_INJECT", None)
        if spec is not None:
            env["ACG_TPU_FAULT_INJECT"] = spec
        report = supervise(
            argv_i, ckpt_path=os.path.join(tmpdir, f"ck{i}"),
            budget=args.relaunch_budget,
            backoff=min(args.relaunch_backoff, 0.2),
            shrink=args.shrink, min_parts=args.min_parts,
            nparts=int(args.nparts or 0), env=env, capture=True,
            label=f"chaos {i}")
        def checked():
            # a green run whose output is missing/unreadable is NOT
            # verified -- it must never pass silently
            try:
                return verify_solution(csr, b, out, args.residual_rtol,
                                       args.residual_atol)
            except Exception:  # noqa: BLE001
                return False, None

        rel = None
        if report["rc"] == 0:
            ok, rel = checked()
            outcome = "converged" if ok else "WRONG-ANSWER"
        elif report.get("outcome") == "gate":
            # drift/SLO gate trips (rc 7/8) describe a COMPLETED solve
            # that wrote its answer: it still owes the campaign a
            # correctness verdict -- a gate-tripped wrong answer is a
            # wrong answer, not an abort
            ok, rel = checked()
            outcome = "gate" if ok else "WRONG-ANSWER"
        else:
            outcome = "agreed-abort"
        tally[outcome] = tally.get(outcome, 0) + 1
        if outcome == "WRONG-ANSWER":
            worst.append((i, spec, rel))
        sys.stderr.write(
            f"acg-tpu: chaos[{i}]: fault={spec or 'none'} "
            f"rc={report['rc']} attempts={report['attempts']} "
            f"-> {outcome}"
            f"{f' (true rel residual {rel:.3e})' if rel is not None else ''}\n")
        if args.history:
            from acg_tpu import observatory
            try:
                observatory.history_append(args.history, _history_recovery_doc(
                    args, report, kind="chaos",
                    extra={"chaos": {
                        "schedule": i, "seed": seed,
                        "fault": spec, "outcome": outcome,
                        "true_rel_residual": rel}}))
            except OSError as e:
                sys.stderr.write(f"acg-tpu: --history {args.history}: "
                                 f"{e}\n")
    sys.stderr.write(
        "chaos:\n"
        f"  schedules: {nsched} (seed {seed})\n"
        f"  converged: {tally['converged']}\n"
        f"  agreed-abort: {tally['agreed-abort']}\n"
        + (f"  gate: {tally['gate']}\n" if tally.get("gate") else "")
        + f"  wrong-answer: {tally['WRONG-ANSWER']}\n")
    for i, spec, rel in worst:
        why = (f"true rel residual {rel:.3e}" if rel is not None
               else "output missing/unreadable")
        sys.stderr.write(f"  WRONG-ANSWER: schedule {i} "
                         f"(fault={spec}, {why})\n")
    if args.metrics_file:
        try:
            metrics.write_textfile(args.metrics_file)
        except OSError as e:
            sys.stderr.write(f"acg-tpu: --metrics-file "
                             f"{args.metrics_file}: {e}\n")
    metrics.disarm()
    if tally["WRONG-ANSWER"]:
        return int(ExitCode.WRONG_ANSWER)
    return 0
