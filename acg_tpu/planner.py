"""Decision observatory: the cost-model-driven program planner.

The repo exposes ~12 meaningfully different compiled programs per
problem (classic / pipelined / sstep:S / pipelined:L recurrences x
assembled / matrix-free operands x xla / dma halo transport x auto /
fused kernels x preconditioners), every one of them hand-picked by
flags.  This module closes ROADMAP item 2's loop: it prices every
candidate program from measurements the observability stack already
produces and emits a ranked ``acg-tpu-plan/1`` document --

    predicted seconds per solve =
        (per-iteration HBM traffic over the probed triad bandwidth
         + per-iteration communication over the commbench-calibrated
           alpha-beta fits, priced over the recurrence's declared
           reduction schedule and the partition's halo-plane bytes
         ) x (iterations from the Lanczos-kappa CG bound, adjusted
              per recurrence)
        + one program dispatch

so S, L and the Chebyshev degree are chosen NUMERICALLY instead of by
flag -- the measurement-driven selection the communication-avoiding CG
literature (Carson's s-step analyses, Cornelis-Cools-Vanroose p(l)-CG)
assumes when picking block sizes for a machine.

Provenance is total: the plan records the calibration id it priced
against (or the clearly-marked ``uncalibrated`` fallback constants),
the kappa source, and a TYPED refusal reason for every pruned cell
(mirroring the CLI's refusal matrices -- a cell the dispatcher would
refuse must never be ranked).  Every planned solve records
plan-vs-actual into the ``--history`` ledger, and the planner consults
prior plan-vs-actual rows for the same (matrix, mesh, calibration) key
to rescale its constants: the model self-corrects across runs.

Everything here is host-side arithmetic over existing ledgers and
fits; building a plan never touches the compiled programs (the
disarmed byte-identity contract, pinned in test_hlo_structure)."""

from __future__ import annotations

import hashlib
import json
import math

import numpy as np

PLAN_SCHEMA = "acg-tpu-plan/1"

# the enumerated recurrence axis: S and L are chosen numerically from
# these, not by flag (sstep basis flips monomial -> chebyshev at S=4,
# recurrence.RecurrenceSpec.basis)
ALGORITHMS = ("classic", "pipelined", "sstep:2", "sstep:4", "sstep:8",
              "pipelined:1", "pipelined:2", "pipelined:3")
KERNEL_CHOICES = ("auto", "fused")
COMM_CHOICES = ("xla", "dma")
# chebyshev degrees enumerated when the requested preconditioner is
# cheby (degree chosen numerically, the S/L rule)
CHEBY_DEGREES = (2, 4, 8)

# uncalibrated comm fallback: a conservative scalar-collective latency
# and the perfmodel ring-hop bandwidth guess.  Plans priced from these
# are CLEARLY marked (doc["uncalibrated"] = True) -- they rank HBM
# against comm honestly enough to order candidates, nothing more
FALLBACK_ALPHA_S = 2e-5
FALLBACK_GBS = 45.0

# iteration-count penalty constants per recurrence: the numerical price
# of a longer basis (s-step monomial conditioning, p(l) z-basis Gram
# degradation) on top of the exact-arithmetic equivalence.  These are
# the constants plan-vs-actual self-correction rescales over runs
SSTEP_MONOMIAL_PENALTY = 0.015   # x (1 + c * S^2)
SSTEP_CHEBY_PENALTY = 0.02      # x (1 + c * S)
PL_PENALTY = 0.03               # x (1 + c * L)
PIPELINED_PENALTY = 0.05        # Ghysels-Vanroose residual-drift lag
# preconditioner spectrum-compression guesses (kappa multipliers) used
# only when ranking a precond cell against "none"; the measured
# kappa(M^-1 A) replaces these wherever a spectrum estimate exists
JACOBI_KAPPA_FACTOR = 0.6
BJACOBI_KAPPA_FACTOR = 0.5

# self-correction window: geometric mean over the last N plan-vs-actual
# rows for the same (matrix, mesh, calibration) key
CORRECTION_WINDOW = 8

# extra vector passes the s-step basis build pays per iteration on top
# of the classic loop's 15 (basis write + read of the 2S+1 block,
# amortised) -- a documented heuristic, rescaled by self-correction
SSTEP_EXTRA_PASSES = 4


# -- candidate enumeration -------------------------------------------------

def _precond_choices(precond) -> list:
    """The precond axis for one requested spec: always "none" (the
    planner may find the unpreconditioned program faster), plus the
    requested kind -- cheby enumerates its degree numerically."""
    choices = ["none"]
    if precond in (None, "", "none"):
        return choices
    p = str(precond)
    if p.startswith("cheby"):
        choices.extend(f"cheby:{k}" for k in CHEBY_DEGREES)
    else:
        choices.append(p)
    return choices


def enumerate_candidates(nparts: int, precond=None, cal: dict | None = None,
                         operator_armed: bool = False,
                         kernels=KERNEL_CHOICES,
                         comms=COMM_CHOICES) -> tuple[list, list]:
    """``(candidates, pruned)`` over the full program space.  Pruned
    cells carry a TYPED reason mirroring the CLI refusal matrices --
    a combination the dispatcher would refuse must never be ranked."""
    from acg_tpu.recurrence import parse_algorithm

    cal_kinds = (cal or {}).get("collectives", {})
    dma_fitted = isinstance(cal_kinds.get("dma"), dict) \
        and "alpha_s" in cal_kinds["dma"]
    candidates, pruned = [], []
    for alg in ALGORITHMS:
        spec = parse_algorithm(alg)
        ca = spec is not None and spec.communication_avoiding
        for kern in kernels:
            for comm in comms:
                for pc in _precond_choices(precond):
                    for matfree in ((False, True) if operator_armed
                                    else (False,)):
                        cand = {"algorithm": alg, "kernels": kern,
                                "comm": comm, "precond": pc,
                                "matrix_free": bool(matfree)}
                        reason = None
                        if ca and pc != "none":
                            reason = ("ca-precond", "the CA recurrences "
                                      "run unpreconditioned (the CLI "
                                      "--algorithm refusal)")
                        elif ca and kern == "fused":
                            reason = ("ca-fused", "--algorithm x "
                                      "--kernels fused is refused by "
                                      "the CLI")
                        elif kern == "fused" and pc != "none":
                            reason = ("fused-precond", "the fused "
                                      "two-phase kernels have no "
                                      "preconditioner hook")
                        elif comm == "dma" and nparts < 2:
                            reason = ("dma-single-part", "the one-sided "
                                      "transport needs a multi-part "
                                      "mesh")
                        elif comm == "dma" and not dma_fitted:
                            reason = ("dma-unbenchmarked", "no dma fit "
                                      "in the calibration; the planner "
                                      "will not price a transport it "
                                      "cannot predict")
                        elif operator_armed and not matfree:
                            reason = ("assembled-bypassed", "--operator "
                                      "is armed; the dispatched "
                                      "programs are matrix-free")
                        if reason is not None:
                            pruned.append({**cand, "reason": reason[0],
                                           "detail": reason[1]})
                        else:
                            candidates.append(cand)
    return candidates, pruned


def candidate_label(cand: dict) -> str:
    tag = "matfree" if cand.get("matrix_free") else "assembled"
    return (f"{cand['algorithm']}/{cand['kernels']}/{cand['comm']}/"
            f"{cand['precond']}/{tag}")


# -- static problem measurements ------------------------------------------

def halo_plane_rows(csr, nparts: int) -> int:
    """Ghost rows of the widest part under the contiguous band
    partition the planner assumes (the dist tier's DIA-friendly
    default): the per-exchange halo plane the transport moves, priced
    in rows (x vector itemsize = bytes).  O(nnz) host arithmetic."""
    n = int(csr.shape[0])
    p = max(int(nparts), 1)
    if p < 2:
        return 0
    bounds = [round(i * n / p) for i in range(p + 1)]
    indptr = np.asarray(csr.indptr)
    indices = np.asarray(csr.indices)
    worst = 0
    for i in range(p):
        lo, hi = bounds[i], bounds[i + 1]
        cols = indices[indptr[lo]:indptr[hi]]
        ghost = np.unique(cols[(cols < lo) | (cols >= hi)])
        worst = max(worst, int(ghost.size))
    return worst


def kappa_estimate(csr, rtol: float, maxits: int,
                   precond=None) -> tuple:
    """``(kappa, source)`` from a traced host-oracle solve + Lanczos
    tridiagonal (the --explain convergence tier's estimator), size-
    guarded exactly like perfmodel._explain_convergence.  ``source``
    is the plan's kappa provenance string."""
    if csr.shape[0] > 200_000 or csr.nnz > 2_000_000:
        return None, "unavailable (matrix too large for the " \
                     "host-oracle Lanczos estimate)"
    from acg_tpu import health
    from acg_tpu.solvers.host_cg import HostCGSolver
    from acg_tpu.solvers.stats import StoppingCriteria

    rt = rtol if 0 < rtol < 1 else 1e-9
    crit = StoppingCriteria(maxits=min(max(int(maxits), 200), 2000),
                            residual_rtol=rt)
    try:
        hs = HostCGSolver(csr, trace=4096, precond=precond)
        hs.solve(np.ones(csr.shape[0]), criteria=crit,
                 raise_on_divergence=False)
        est = health.spectrum_estimate(hs.last_trace)
    except Exception as e:  # noqa: BLE001 -- the plan degrades, never sinks
        return None, f"unavailable ({type(e).__name__})"
    kappa = (est or {}).get("kappa")
    if not kappa or kappa <= 0:
        return None, "unavailable (non-positive Ritz value)"
    return float(kappa), "lanczos-oracle"


# -- pricing ---------------------------------------------------------------

def _fit_or_fallback(cal: dict | None, kind: str) -> tuple[dict, bool]:
    """The alpha-beta fit for one collective kind, or the clearly-
    marked uncalibrated fallback constants."""
    fit = (cal or {}).get("collectives", {}).get(kind)
    if isinstance(fit, dict) and "alpha_s" in fit:
        return fit, True
    return {"alpha_s": FALLBACK_ALPHA_S,
            "beta_s_per_byte": 1.0 / (FALLBACK_GBS * 1e9)}, False


def _iterations_for(cand: dict, kappa, rtol: float, maxits: int) -> tuple:
    """``(predicted_iterations, kappa_effective)`` for one candidate:
    the Lanczos-kappa CG bound through the precond's spectrum
    compression, inflated by the recurrence's numerical penalty."""
    from acg_tpu.health import predicted_iterations
    from acg_tpu.recurrence import parse_algorithm

    pc = cand["precond"]
    keff = kappa
    if keff is not None:
        if pc == "jacobi":
            keff = keff * JACOBI_KAPPA_FACTOR
        elif pc.startswith("bjacobi"):
            keff = keff * BJACOBI_KAPPA_FACTOR
        elif pc.startswith("cheby:"):
            deg = int(pc.split(":", 1)[1])
            keff = max(keff / float(deg * deg), 1.0 + 1e-9)
    base = predicted_iterations(keff, rtol) if keff else None
    if base is None:
        base = int(maxits)
    spec = parse_algorithm(cand["algorithm"])
    mult = 1.0
    if spec is not None and spec.kind == "sstep":
        mult = (1.0 + SSTEP_CHEBY_PENALTY * spec.param
                if spec.basis == "chebyshev"
                else 1.0 + SSTEP_MONOMIAL_PENALTY * spec.param ** 2)
    elif spec is not None and spec.kind == "pl":
        mult = 1.0 + PL_PENALTY * spec.param
    elif cand["algorithm"] == "pipelined":
        mult = 1.0 + PIPELINED_PENALTY
    its = max(1, min(int(math.ceil(base * mult)), int(maxits)))
    return its, keff


def price_candidate(cand: dict, ctx: dict) -> dict:
    """One candidate's predicted cost breakdown.  ``ctx`` carries the
    problem measurements (n, nnz, itemsizes, halo rows), the probed
    constants (bw_gbs, dispatch_s), the calibration doc (or None) and
    the kappa/rtol/maxits convergence inputs."""
    from acg_tpu.commbench import predict_seconds
    from acg_tpu.recurrence import parse_algorithm, reduction_schedule

    n, nnz = int(ctx["n"]), int(ctx["nnz"])
    vec_b = int(ctx["vec_itemsize"])
    spec = parse_algorithm(cand["algorithm"])
    pipelined = cand["algorithm"] == "pipelined"
    pc = cand["precond"]
    schedule = reduction_schedule(spec, pipelined,
                                  precond=pc != "none")
    its, keff = _iterations_for(cand, ctx.get("kappa"), ctx["rtol"],
                                ctx["maxits"])

    # per-iteration HBM traffic: matrix reads (zero for matrix-free --
    # the stencil is recomputed) x the recurrence's SpMV count, the
    # loop's vector passes, and the preconditioner apply
    mat_bytes = 0.0 if cand["matrix_free"] \
        else nnz * (ctx["mat_itemsize"] + ctx["idx_bytes"])
    spmv_mult = float(schedule.get("spmv_per_iteration", 1.0))
    passes = 21 if (pipelined or (spec is not None
                                  and spec.kind == "pl")) else 15
    if spec is not None and spec.kind == "sstep":
        passes += SSTEP_EXTRA_PASSES
    hbm_bytes = mat_bytes * spmv_mult + passes * n * vec_b
    halo_exchanges = spmv_mult
    if pc != "none":
        from acg_tpu.precond import bytes_per_apply, parse_precond
        pspec = parse_precond(pc)
        hbm_bytes += bytes_per_apply(pspec, n, vec_b, mat_bytes,
                                     state_bytes=float(n * vec_b))
        if pspec.kind == "cheby":
            halo_exchanges += pspec.degree
    bw = ctx.get("bw_gbs") or FALLBACK_GBS
    t_hbm = hbm_bytes / (bw * 1e9)

    # per-iteration communication from the calibrated alpha-beta fits
    # over the recurrence's declared reduction schedule and the
    # partition's halo-plane bytes
    t_ar = t_halo = 0.0
    nparts = int(ctx["nparts"])
    calibrated = True
    if nparts > 1:
        ar_fit, ar_cal = _fit_or_fallback(ctx.get("cal"), "all_reduce")
        nred = float(schedule.get("allreduce_per_iteration", 0.0))
        scalars = float(schedule.get("allreduce_scalars", 1))
        t_ar = nred * float(predict_seconds(ar_fit, scalars * vec_b))
        hidden = float(schedule.get("reduction_latency_hidden", 0) or 0)
        if hidden > 0:
            # p(l): the fused allreduce overlaps L SpMV steps -- only
            # the latency the matrix traffic cannot cover is exposed
            t_ar = max(0.0, t_ar - hidden * mat_bytes / (bw * 1e9))
        halo_kind = "dma" if cand["comm"] == "dma" else "all_to_all"
        halo_fit, halo_cal = _fit_or_fallback(ctx.get("cal"), halo_kind)
        halo_bytes = float(ctx.get("halo_rows", 0)) * vec_b
        t_one = float(predict_seconds(halo_fit, halo_bytes))
        if cand["kernels"] == "fused":
            # the fused tier's overlap: interior SpMV traffic hides the
            # halo (perfmodel.predicted_overlap_seconds, restated)
            t_int = (mat_bytes / nparts) / (bw * 1e9)
            t_one = max(0.0, t_one - t_int)
        t_halo = t_one * halo_exchanges
        calibrated = ar_cal and halo_cal
    t_comm = t_ar + t_halo
    t_iter = t_hbm + t_comm
    disp = float(ctx.get("dispatch_s") or 0.0)
    total = its * t_iter + disp
    comp = {"hbm": its * t_hbm, "comm": its * t_comm, "dispatch": disp}
    dominant = max(comp, key=lambda k: comp[k])
    return {
        **cand,
        "label": candidate_label(cand),
        "predicted_iterations": int(its),
        "kappa_effective": (round(float(keff), 6)
                            if keff is not None else None),
        "s_per_iteration": {"hbm": t_hbm, "allreduce": t_ar,
                            "halo": t_halo},
        "components_s": comp,
        "dominant": dominant,
        "predicted_s_per_solve": float(total),
        "calibrated": bool(calibrated),
    }


# -- plan-vs-actual self-correction ---------------------------------------

def plan_key(matrix_id, nparts, calibration) -> str:
    """The self-correction join key: plans and plan-vs-actual rows for
    the same matrix on the same mesh under the same calibration."""
    return f"{matrix_id}|{int(nparts)}p|{calibration}"


def consult_history(history_dir, matrix_id, nparts,
                    calibration) -> dict:
    """Scan the run-history ledger for prior plan-vs-actual rows under
    the same (matrix, mesh, calibration) key and derive the constant
    rescale: the geometric mean of measured/predicted seconds-per-solve
    over the last :data:`CORRECTION_WINDOW` rows.  ``{"scale",
    "nsamples"}`` -- scale 1.0 when nothing usable exists (first run,
    missing ledger, other keys)."""
    out = {"scale": 1.0, "nsamples": 0}
    if not history_dir:
        return out
    from acg_tpu import observatory

    key = plan_key(matrix_id, nparts, calibration)
    ratios = []
    for entry in observatory.history_scan(history_dir):
        doc = entry.get("doc") or {}
        plan = ((doc.get("stats") or {}).get("plan")) or {}
        if plan.get("key") != key:
            continue
        pred = plan.get("predicted_s_per_solve")
        meas = plan.get("measured_s_per_solve")
        try:
            pred, meas = float(pred), float(meas)
        except (TypeError, ValueError):
            continue
        if pred > 0 and meas > 0 and math.isfinite(pred) \
                and math.isfinite(meas):
            ratios.append(meas / pred)
    ratios = ratios[-CORRECTION_WINDOW:]
    if ratios:
        out["scale"] = float(math.exp(
            sum(math.log(r) for r in ratios) / len(ratios)))
        out["nsamples"] = len(ratios)
    return out


# -- the ranked plan document ---------------------------------------------

def plan_id(doc: dict) -> str:
    """Content-hashed plan id (the calibration_id pattern): any edit to
    the ranking produces a different id."""
    payload = {k: v for k, v in doc.items() if k != "plan_id"}
    h = hashlib.sha256(json.dumps(payload, sort_keys=True,
                                  default=str).encode()).hexdigest()
    return (f"plan-{doc.get('backend', 'x')}-"
            f"{int(doc.get('nparts', 0))}p-{h[:10]}")


def build_plan(csr, *, matrix_id, nparts, dtype_name, rtol, maxits,
               mat_itemsize, vec_itemsize, idx_bytes=4.0,
               precond=None, cal=None, kappa=None,
               kappa_source="unavailable", bw_gbs=None,
               dispatch_s=None, history_dir=None, backend="cpu",
               operator_armed=False, kernels=KERNEL_CHOICES,
               comms=COMM_CHOICES) -> dict:
    """Price the candidate space for one problem and emit the ranked
    ``acg-tpu-plan/1`` document.  Pure host arithmetic: same inputs +
    same calibration => byte-identical document (the determinism
    contract; no timestamps live inside)."""
    from acg_tpu.commbench import UNCALIBRATED

    cal_id = (cal or {}).get("calibration_id") or UNCALIBRATED
    candidates, pruned = enumerate_candidates(
        nparts, precond=precond, cal=cal,
        operator_armed=operator_armed, kernels=kernels, comms=comms)
    correction = consult_history(history_dir, matrix_id, nparts, cal_id)
    ctx = {"n": int(csr.shape[0]), "nnz": int(csr.nnz),
           "mat_itemsize": float(mat_itemsize),
           "vec_itemsize": int(vec_itemsize),
           "idx_bytes": float(idx_bytes),
           "halo_rows": halo_plane_rows(csr, nparts),
           "nparts": int(nparts), "cal": cal, "kappa": kappa,
           "rtol": float(rtol), "maxits": int(maxits),
           "bw_gbs": bw_gbs, "dispatch_s": dispatch_s}
    ranked = [price_candidate(c, ctx) for c in candidates]
    scale = float(correction["scale"])
    for row in ranked:
        row["predicted_s_per_solve"] = \
            row["predicted_s_per_solve"] * scale
        row["components_s"] = {k: v * scale
                               for k, v in row["components_s"].items()}
    # deterministic ranking: time, then label (a stable tie-break so
    # equal-cost cells never reorder between runs)
    ranked.sort(key=lambda r: (r["predicted_s_per_solve"], r["label"]))
    doc = {
        "schema": PLAN_SCHEMA,
        "matrix": str(matrix_id),
        "nparts": int(nparts),
        "dtype": str(dtype_name),
        "rtol": float(rtol),
        "maxits": int(maxits),
        "backend": str(backend),
        "calibration": cal_id,
        "uncalibrated": cal is None,
        "kappa": (round(float(kappa), 6) if kappa else None),
        "kappa_source": str(kappa_source),
        "bw_gbs": (round(float(bw_gbs), 3) if bw_gbs else None),
        "dispatch_s": (float(dispatch_s) if dispatch_s else None),
        "halo_plane_rows": int(ctx["halo_rows"]),
        "correction": {"scale": round(scale, 6),
                       "nsamples": int(correction["nsamples"]),
                       "key": plan_key(matrix_id, nparts, cal_id)},
        "ranked": ranked,
        "pruned": pruned,
    }
    doc["plan_id"] = plan_id(doc)
    return doc


def validate_plan(doc) -> list[str]:
    """Problems with a plan document (empty list = valid): schema, id
    integrity, a non-empty ranking with finite predictions, and typed
    reasons on every pruned cell."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return ["not a JSON object"]
    if doc.get("schema") != PLAN_SCHEMA:
        problems.append(f"schema is {doc.get('schema')!r}, expected "
                        f"{PLAN_SCHEMA!r}")
        return problems
    pid = doc.get("plan_id")
    if not isinstance(pid, str) or not pid:
        problems.append("missing plan_id")
    elif pid != plan_id(doc):
        problems.append("plan_id does not match the document content "
                        "(edited after planning?)")
    ranked = doc.get("ranked")
    if not isinstance(ranked, list) or not ranked:
        problems.append("empty ranking")
        return problems
    for row in ranked:
        if not isinstance(row, dict):
            problems.append(f"bad ranked row {row!r}")
            break
        t = row.get("predicted_s_per_solve")
        if not isinstance(t, (int, float)) or not math.isfinite(t) \
                or t < 0:
            problems.append(f"{row.get('label')}: non-finite "
                            f"prediction {t!r}")
            break
    times = [r.get("predicted_s_per_solve", 0) for r in ranked
             if isinstance(r, dict)]
    if times != sorted(times):
        problems.append("ranking is not sorted by predicted time")
    for cell in doc.get("pruned") or []:
        if not isinstance(cell, dict) or not cell.get("reason"):
            problems.append(f"pruned cell without a typed reason: "
                            f"{cell!r}")
            break
    if not isinstance(doc.get("calibration"), str):
        problems.append("missing calibration provenance")
    return problems


def render_plan(doc: dict, limit: int = 12) -> str:
    """The human-readable ranked table (--explain --plan)."""
    lines = [f"== plan: {doc['matrix']} on {doc['nparts']} part(s), "
             f"{doc['dtype']}, rtol {doc['rtol']:g} ==",
             f"  plan {doc['plan_id']}; calibration "
             f"{doc['calibration']}"
             + ("  ** UNCALIBRATED: comm priced from fallback "
                "constants **" if doc.get("uncalibrated") else ""),
             f"  kappa "
             + (f"{doc['kappa']:.4g} ({doc['kappa_source']})"
                if doc.get("kappa") else f"{doc['kappa_source']}")
             + (f"; correction x{doc['correction']['scale']:.3f} over "
                f"{doc['correction']['nsamples']} prior run(s)"
                if doc["correction"]["nsamples"] else
                "; no prior plan-vs-actual rows (correction x1.000)")]
    head = (f"  {'#':>2}  {'candidate':<42} {'pred s/solve':>12} "
            f"{'iters':>6}  dominant")
    lines.append(head)
    for i, row in enumerate(doc["ranked"][:limit], 1):
        lines.append(f"  {i:>2}  {row['label']:<42} "
                     f"{row['predicted_s_per_solve']:>12.3e} "
                     f"{row['predicted_iterations']:>6}  "
                     f"{row['dominant']}")
    extra = len(doc["ranked"]) - limit
    if extra > 0:
        lines.append(f"  ... {extra} more candidate(s)")
    if doc.get("pruned"):
        reasons: dict[str, int] = {}
        for cell in doc["pruned"]:
            reasons[cell["reason"]] = reasons.get(cell["reason"], 0) + 1
        pr = ", ".join(f"{k} x{v}" for k, v in sorted(reasons.items()))
        lines.append(f"  pruned {len(doc['pruned'])} cell(s): {pr}")
    return "\n".join(lines) + "\n"


def write_plan(doc: dict, dest) -> None:
    """Write the plan doc to a path (``"-"`` = stdout)."""
    import sys
    if dest in (None, "-"):
        json.dump(doc, sys.stdout, indent=2)
        sys.stdout.write("\n")
        return
    with open(dest, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")


# -- CLI integration -------------------------------------------------------

def _probe_constants(vec_dtype, on_tpu: bool, use_cache: bool = True):
    """``(bw_gbs, dispatch_s)`` from the perfmodel probes (both behind
    their existing caches/guards); (None, None) when probing fails."""
    from acg_tpu.perfmodel import _dispatch_seconds, \
        cached_triad_probe_gbs

    bw = disp = None
    try:
        bw = (cached_triad_probe_gbs(use_cache=use_cache) if on_tpu
              else cached_triad_probe_gbs(1 << 22, use_cache=use_cache,
                                          lo=0.5))
    except Exception:  # noqa: BLE001 -- fallback constants take over
        pass
    try:
        disp = _dispatch_seconds(dtype=vec_dtype)
    except Exception:  # noqa: BLE001
        pass
    return bw, disp


def plan_for_args(args, csr, nparts, dtype, vec_dtype) -> dict:
    """Build the plan for one CLI invocation (the --plan/--autotune
    entry): probes, kappa estimate, calibration and history pickup all
    come from the same sources the explain tier uses."""
    import jax

    on_tpu = jax.default_backend() == "tpu"
    use_cache = not getattr(args, "no_probe_cache", False)
    bw, disp = _probe_constants(vec_dtype, on_tpu, use_cache=use_cache)
    kappa, source = kappa_estimate(csr, args.residual_rtol,
                                   args.max_iterations)
    pc = getattr(args, "_precond", None)
    return build_plan(
        csr, matrix_id=str(args.A), nparts=int(nparts),
        dtype_name=str(args.dtype), rtol=float(args.residual_rtol),
        maxits=int(args.max_iterations),
        mat_itemsize=np.dtype(dtype).itemsize,
        vec_itemsize=np.dtype(vec_dtype).itemsize,
        precond=(str(pc) if pc is not None else None),
        cal=getattr(args, "_calibration", None),
        kappa=kappa, kappa_source=source, bw_gbs=bw, dispatch_s=disp,
        history_dir=getattr(args, "history", None),
        backend=jax.default_backend(),
        operator_armed=getattr(args, "_operator_spec", None)
        is not None)


def run_plan_explain(args, dtype, vec_dtype) -> int:
    """``--explain --plan``: print the ranked table WITHOUT solving
    (and write the plan document when --plan names a FILE).  The
    no-dispatch twin of the autotune path."""
    import sys

    from acg_tpu.perfmodel import _explain_matrix

    csr = _explain_matrix(args)
    import jax
    nparts = args.nparts or min(len(jax.devices()), 4)
    doc = plan_for_args(args, csr, nparts, dtype, vec_dtype)
    sys.stderr.write(render_plan(doc))
    if args.plan not in (None, "-"):
        try:
            write_plan(doc, args.plan)
        except OSError as e:
            sys.stderr.write(f"acg-tpu: --plan {args.plan}: {e}\n")
            return 1
    return 0


def apply_candidate_to_args(args, cand: dict) -> str:
    """Mutate the parsed CLI args so the NORMAL construction flow
    dispatches the chosen candidate -- the planner only ever chooses
    flags before construction, never alters program emission (the
    disarmed byte-identity contract).  Returns the resolved comm."""
    from acg_tpu.precond import parse_precond
    from acg_tpu.recurrence import parse_algorithm

    alg = cand["algorithm"]
    spec = parse_algorithm(alg)
    if spec is not None and spec.communication_avoiding:
        args.solver = "acg"
        args._algorithm = spec
    else:
        args.solver = "acg-pipelined" if alg == "pipelined" else "acg"
        args._algorithm = None
    args.kernels = cand["kernels"]
    args._precond = parse_precond(None if cand["precond"] == "none"
                                  else cand["precond"])
    args.comm = cand["comm"]
    return cand["comm"]


def _probe_candidate(cand: dict, csr, part, nparts, b, dtype,
                     vec_dtype, args, probe_its: int) -> float | None:
    """One short timed probe of a candidate: build the solver the way
    the CLI would, run ``probe_its`` iterations once warm, return
    seconds (None when the candidate fails to build/run)."""
    import jax.numpy as jnp

    from acg_tpu.precond import parse_precond
    from acg_tpu.recurrence import parse_algorithm
    from acg_tpu.solvers.stats import StoppingCriteria

    spec = parse_algorithm(cand["algorithm"])
    pipelined = cand["algorithm"] == "pipelined"
    algorithm = spec if spec is not None \
        and spec.communication_avoiding else None
    pc = parse_precond(None if cand["precond"] == "none"
                       else cand["precond"])
    crit = StoppingCriteria(maxits=int(probe_its), residual_rtol=0.0,
                            residual_atol=0.0)
    import time as _time
    try:
        if nparts > 1:
            from acg_tpu.parallel.dist import (DistCGSolver,
                                               DistributedProblem,
                                               resolve_comm)
            prob = DistributedProblem.build(csr, part, nparts,
                                            dtype=dtype,
                                            vector_dtype=vec_dtype)
            solver = DistCGSolver(prob, pipelined=pipelined,
                                  comm=resolve_comm(cand["comm"]),
                                  kernels=cand["kernels"],
                                  precond=pc, algorithm=algorithm)
        else:
            from acg_tpu.ops.spmv import device_matrix_from_csr
            from acg_tpu.solvers.jax_cg import JaxCGSolver
            A = device_matrix_from_csr(csr, dtype=dtype)
            solver = JaxCGSolver(A, pipelined=pipelined,
                                 kernels=cand["kernels"],
                                 vector_dtype=vec_dtype,
                                 precond=pc, algorithm=algorithm,
                                 host_matrix=csr)
        solver.solve(jnp.asarray(b), criteria=crit, warmup=1)
        t0 = _time.perf_counter()
        solver.solve(jnp.asarray(b), criteria=crit)
        return _time.perf_counter() - t0
    except Exception:  # noqa: BLE001 -- a failing probe disqualifies
        return None    # the candidate, never the solve


def autotune_select(args, doc: dict, csr, part, nparts, b, dtype,
                    vec_dtype, err, top: int = 2,
                    probe_its: int = 8) -> dict | None:
    """Verify the plan's top candidates by short timed probes and
    return the winner's ranked row (None when every probe failed --
    the caller falls back to the flag-selected program)."""
    rows = doc["ranked"][:max(int(top), 1)]
    timed = []
    for row in rows:
        s = _probe_candidate(row, csr, part, nparts, b, dtype,
                             vec_dtype, args, probe_its)
        if s is not None:
            timed.append((s, row))
            err.write(f"acg-tpu: autotune: probe {row['label']}: "
                      f"{s:.4g}s / {probe_its} its\n")
        else:
            err.write(f"acg-tpu: autotune: probe {row['label']} "
                      f"failed; candidate disqualified\n")
    if not timed:
        return None
    timed.sort(key=lambda t: t[0])
    return timed[0][1]
