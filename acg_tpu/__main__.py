"""``python -m acg_tpu`` runs the acg-tpu CLI driver."""

import sys

from acg_tpu.cli import main

sys.exit(main())
