"""Solver-state checkpoint/restore -- the survivability substrate.

The reference paper's target regime (long CG runs over large meshes on
big clusters) is exactly where the two failure classes the resilience
tier (solvers/resilience) cannot survive dominate: process/host death
(pod preemption, a controller OOM mid-solve) and silent data corruption
that never trips a non-finite guard.  This module supplies the first
half of the fix -- periodic **solver-state snapshots** to disk -- and
the plumbing the second half (the ABFT checksum SpMV in
:mod:`acg_tpu.health` and the rollback rung in
:mod:`acg_tpu.solvers.resilience`) restores from.

Design:

* The compiled solve loops cannot be interrupted mid-dispatch, so an
  armed checkpoint (``--ckpt FILE --ckpt-every K``) turns the solve
  into a host-driven CHUNK loop: each dispatch runs at most K
  iterations of the UNCHANGED recurrence with the full loop carry
  (x, r, p, pipelined extras, the preconditioned ``rr``) threaded in
  and out of the program (``state_io``/``carry`` -- static/pytree
  arguments the disarmed programs never name, so a build without
  ``--ckpt`` lowers byte-identical code; pinned in
  tests/test_checkpoint.py).  Because the carry continues the Krylov
  recurrence exactly, a chunked solve follows the identical iteration
  trajectory as an uninterrupted one -- no restart penalty per
  snapshot.
* Snapshots are written with ATOMIC RENAME (a crash mid-write leaves
  the previous snapshot intact, never a torn file) and carry a
  CHECKSUMMED header + payload (CRC32): a corrupted file refuses to
  load instead of resuming a solve from garbage.
* ``--resume FILE`` reconstructs the carry and continues to the
  ORIGINAL tolerance: the snapshot stores the absolute residual target
  derived from the first attempt's ``r0`` (the recovery-restart
  convention), so resumed chunks never re-baseline ``rtol`` against an
  already-small residual.  Total iterations (pre-crash + post-resume)
  match an uninterrupted run exactly, well inside the acceptance
  criterion's 10% slack.
* On the distributed tier every per-part carry leaf is gathered
  host-side and the snapshot commits under ONE agreed sequence number
  (:func:`agree_seq` over the erragree plumbing), so all ranks hold
  the same iteration; the primary writes the file.

The snapshot also records the fault-injection residue (so a
deterministic ``crash:exit@K`` does not re-fire after resume -- see
:func:`acg_tpu.faults.maybe_crash`'s crossing semantics) and the
trailing telemetry-ring window (small, JSON) for post-mortem evidence.
"""

from __future__ import annotations

import dataclasses
import json
import os
import zlib

import numpy as np

from acg_tpu.errors import AcgError, ErrorCode

MAGIC = b"ACGCKPT1\n"
# snapshot container version (bump on layout changes; readers refuse
# versions they do not know rather than misparse)
VERSION = 1
# exit code of a crash:exit fault firing (distinct from peer:dead's 86
# and erragree's PEER_LOST_EXIT 97; in the 64..113 hole shell
# conventions leave free)
CRASH_EXIT_CODE = 94


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    """The armed checkpoint selection a solver carries.

    ``path`` is where snapshots land (None = resume-only: continue a
    crashed solve without writing further snapshots); ``every`` the
    chunk length in iterations (must be positive when ``path`` is
    set); ``resume`` a loaded :class:`SolverSnapshot` consumed by the
    first solve."""

    path: str | None = None
    every: int = 0
    resume: "SolverSnapshot | None" = None

    def __post_init__(self):
        if self.path is not None and self.every <= 0:
            raise ValueError("checkpointing needs a positive snapshot "
                             "period (ckpt_every K)")
        if self.path is None and self.resume is None:
            raise ValueError("a CheckpointConfig needs a snapshot path "
                             "and/or a snapshot to resume from")

    @property
    def chunk(self) -> int:
        """The host chunk length: the snapshot period, or (resume-only
        configurations) unbounded -- one final chunk to convergence."""
        return self.every if self.every > 0 else 1 << 30


@dataclasses.dataclass
class SolverSnapshot:
    """One loaded snapshot: validated metadata + named host arrays."""

    meta: dict
    arrays: dict

    @property
    def iteration(self) -> int:
        return int(self.meta["iteration"])


# the carry leaves that are psum'd scalars (mesh tiers: replicated,
# not sharded) -- everything else is a per-part vector
SCALAR_LEAVES = frozenset({"gamma", "alpha", "rr"})


def carry_names(pipelined: bool, precond: bool) -> tuple:
    """The canonical order of the loop-carry leaves a snapshot stores
    (x first, then the recurrence vectors, then the scalars) -- ONE
    layout shared by the snapshot writer, the resume reconstruction,
    and every tier's ``state_io`` program outputs, so the single- and
    multi-part tiers' snapshots stay field-compatible."""
    if not pipelined:
        names = ("x", "r", "p", "gamma")
        return names + (("rr",) if precond else ())
    if precond:
        return ("x", "r", "u", "w", "p", "s", "q", "z",
                "gamma", "alpha", "rr")
    return ("x", "r", "w", "p", "t", "z", "gamma", "alpha")


def _crc(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def vector_checksum(v) -> int:
    """CRC32 of a host vector's bytes -- stored for ``b`` so a resume
    against a different right-hand side refuses instead of silently
    continuing somebody else's solve."""
    return _crc(np.ascontiguousarray(np.asarray(v)).tobytes())


def save_snapshot(path, meta: dict, arrays: dict) -> int:
    """Write one snapshot atomically; returns the byte size.

    Layout: ``MAGIC`` + one header line
    ``{version, header_crc, payload_crc, header_len}`` + the JSON
    header (meta + per-array manifest) + the raw little-endian array
    payload.  The file lands under a temporary name and is
    ``os.replace``d into place, so a crash mid-write can never leave a
    torn snapshot where a good one stood."""
    manifest = []
    blobs = []
    off = 0
    for name, arr in arrays.items():
        a = np.asarray(arr)
        # record the shape BEFORE ascontiguousarray: it promotes 0-d
        # scalars (the carried gamma/alpha/rr) to shape (1,), which
        # would resume a scalar as a 1-vector and break the loop carry
        shape = list(a.shape)
        raw = np.ascontiguousarray(a).tobytes()
        manifest.append({"name": str(name), "dtype": str(a.dtype),
                         "shape": shape, "offset": off,
                         "nbytes": len(raw)})
        blobs.append(raw)
        off += len(raw)
    payload = b"".join(blobs)
    header = json.dumps({"meta": meta, "arrays": manifest},
                        sort_keys=True).encode("utf-8")
    preamble = json.dumps({"version": VERSION,
                           "header_crc": _crc(header),
                           "payload_crc": _crc(payload),
                           "header_len": len(header)}).encode("utf-8")
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(MAGIC)
            f.write(preamble + b"\n")
            f.write(header)
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
    # live-observatory tier: a committed snapshot is status evidence
    # (the operator's "how stale would a resume be" question; no-op
    # disarmed)
    from acg_tpu import observatory
    observatory.note_event(
        "snapshot", f"seq {meta.get('seq', '?')} committed at "
                    f"iteration {meta.get('iteration', '?')}")
    return len(MAGIC) + len(preamble) + 1 + len(header) + len(payload)


def load_snapshot(path) -> SolverSnapshot:
    """Read + verify one snapshot; raises a typed
    :class:`~acg_tpu.errors.AcgError` on any integrity failure (bad
    magic, unknown version, header or payload checksum mismatch,
    truncation) -- a resumed solve must never start from garbage."""
    def bad(why: str):
        return AcgError(ErrorCode.INVALID_VALUE,
                        f"{path}: not a usable snapshot ({why})")

    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        raise AcgError(ErrorCode.INVALID_VALUE, f"{path}: {e}")
    if not blob.startswith(MAGIC):
        raise bad("bad magic; not an acg-tpu snapshot")
    rest = blob[len(MAGIC):]
    nl = rest.find(b"\n")
    if nl < 0:
        raise bad("truncated preamble")
    try:
        pre = json.loads(rest[:nl])
    except ValueError:
        raise bad("unparseable preamble")
    if int(pre.get("version", -1)) != VERSION:
        raise bad(f"unknown snapshot version {pre.get('version')!r}")
    hlen = int(pre["header_len"])
    header = rest[nl + 1: nl + 1 + hlen]
    payload = rest[nl + 1 + hlen:]
    if len(header) != hlen:
        raise bad("truncated header")
    if _crc(header) != int(pre["header_crc"]):
        raise bad("header checksum mismatch")
    if _crc(payload) != int(pre["payload_crc"]):
        raise bad("payload checksum mismatch")
    doc = json.loads(header)
    arrays = {}
    for m in doc["arrays"]:
        start, n = int(m["offset"]), int(m["nbytes"])
        raw = payload[start: start + n]
        if len(raw) != n:
            raise bad(f"array {m['name']!r} truncated")
        arrays[m["name"]] = np.frombuffer(
            raw, dtype=np.dtype(m["dtype"])).reshape(m["shape"]).copy()
    return SolverSnapshot(meta=doc["meta"], arrays=arrays)


def validate_resume(snap: SolverSnapshot, *, tier: str, pipelined: bool,
                    precond: str | None, n: int, dtype,
                    b_crc: int | None = None,
                    nparts: int | None = None) -> None:
    """Refuse a snapshot that does not describe THIS solve: wrong tier,
    algorithm, preconditioner, size, dtype, partition count, or
    right-hand side.  A mismatch here means the operator pointed
    ``--resume`` at somebody else's solve -- continuing would converge
    to the wrong answer with a green exit code."""
    m = snap.meta

    def need(key, want, what):
        got = m.get(key)
        if got != want:
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                f"snapshot does not match this solve: {what} is "
                f"{got!r}, this run has {want!r}")

    need("tier", tier, "solver tier")
    need("pipelined", bool(pipelined), "algorithm (pipelined)")
    need("precond", precond, "preconditioner")
    need("n", int(n), "unknowns")
    need("dtype", str(np.dtype(dtype)), "vector dtype")
    if nparts is not None:
        need("nparts", int(nparts), "partition count")
    if b_crc is not None and m.get("b_crc") is not None:
        need("b_crc", int(b_crc), "right-hand-side checksum")


def agree_seq(seq: int, iteration: int, timeout: float = 120.0) -> None:
    """Multi-controller snapshot commit barrier: every controller
    reports its (sequence, iteration) pair and all verify the pod holds
    ONE agreed state before the primary writes -- a snapshot whose
    ranks disagree on the iteration number is corruption with a valid
    checksum.  Single-process: free."""
    import jax

    if jax.process_count() == 1:
        return
    from acg_tpu.parallel.erragree import allgather_blobs

    mine = f"{int(seq)}:{int(iteration)}"
    got = allgather_blobs(mine, tag="ckpt-seq", timeout=timeout)
    if any(g != mine for g in got):
        raise AcgError(
            ErrorCode.INVALID_VALUE,
            f"snapshot sequence disagreement across controllers: "
            f"{sorted(set(got))} (mine {mine}) -- refusing to commit")


def trace_tail(trace, n: int = 8) -> list:
    """The trailing telemetry-ring rows as small JSON-able dicts (the
    snapshot's post-mortem evidence; [] without a trace)."""
    if trace is None:
        return []
    m = min(int(n), trace.iterations.size)
    return [trace.record_dict(trace.iterations.size - m + i)
            for i in range(m)]
