"""Solver-state checkpoint/restore -- the survivability substrate.

The reference paper's target regime (long CG runs over large meshes on
big clusters) is exactly where the two failure classes the resilience
tier (solvers/resilience) cannot survive dominate: process/host death
(pod preemption, a controller OOM mid-solve) and silent data corruption
that never trips a non-finite guard.  This module supplies the first
half of the fix -- periodic **solver-state snapshots** to disk -- and
the plumbing the second half (the ABFT checksum SpMV in
:mod:`acg_tpu.health` and the rollback rung in
:mod:`acg_tpu.solvers.resilience`) restores from.

Design:

* The compiled solve loops cannot be interrupted mid-dispatch, so an
  armed checkpoint (``--ckpt FILE --ckpt-every K``) turns the solve
  into a host-driven CHUNK loop: each dispatch runs at most K
  iterations of the UNCHANGED recurrence with the full loop carry
  (x, r, p, pipelined extras, the preconditioned ``rr``) threaded in
  and out of the program (``state_io``/``carry`` -- static/pytree
  arguments the disarmed programs never name, so a build without
  ``--ckpt`` lowers byte-identical code; pinned in
  tests/test_checkpoint.py).  Because the carry continues the Krylov
  recurrence exactly, a chunked solve follows the identical iteration
  trajectory as an uninterrupted one -- no restart penalty per
  snapshot.
* Snapshots are written with ATOMIC RENAME (a crash mid-write leaves
  the previous snapshot intact, never a torn file) and carry a
  CHECKSUMMED header + payload (CRC32): a corrupted file refuses to
  load instead of resuming a solve from garbage.
* ``--resume FILE`` reconstructs the carry and continues to the
  ORIGINAL tolerance: the snapshot stores the absolute residual target
  derived from the first attempt's ``r0`` (the recovery-restart
  convention), so resumed chunks never re-baseline ``rtol`` against an
  already-small residual.  Total iterations (pre-crash + post-resume)
  match an uninterrupted run exactly, well inside the acceptance
  criterion's 10% slack.
* On the distributed tier every per-part carry leaf is gathered
  host-side and the snapshot commits under ONE agreed sequence number
  (:func:`agree_seq` over the erragree plumbing), so all ranks hold
  the same iteration; the primary writes the file.

The snapshot also records the fault-injection residue (so a
deterministic ``crash:exit@K`` does not re-fire after resume -- see
:func:`acg_tpu.faults.maybe_crash`'s crossing semantics) and the
trailing telemetry-ring window (small, JSON) for post-mortem evidence.
"""

from __future__ import annotations

import dataclasses
import json
import os
import zlib

import numpy as np

from acg_tpu.errors import AcgError, ErrorCode, ExitCode

MAGIC = b"ACGCKPT1\n"
# snapshot container version (bump on layout changes; readers refuse
# versions they do not know rather than misparse).  Version 1 files
# remain readable: the repartition sidecar and env metadata are
# ADDITIVE (absent keys degrade to refusals/no-ops, never misparses)
VERSION = 1
# exit code of a crash:exit fault firing (the process-wide contract
# lives in errors.ExitCode; distinct from peer:dead's 86 and the
# erragree teardown's 97)
CRASH_EXIT_CODE = int(ExitCode.CRASH_INJECTED)


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    """The armed checkpoint selection a solver carries.

    ``path`` is where snapshots land (None = resume-only: continue a
    crashed solve without writing further snapshots); ``every`` the
    chunk length in iterations; ``secs`` the WALL-CLOCK snapshot
    cadence (mutually exclusive with ``every`` -- slow iterations
    would otherwise stretch the loss window unboundedly; the chunk
    drivers size each chunk from the measured s/iteration so one chunk
    targets ~``secs`` of wall time); ``resume`` a loaded
    :class:`SolverSnapshot` consumed by the first solve;
    ``repartition`` opts into SHAPE-PORTABLE resume: an N-part
    snapshot restores onto this solver's (different) partition via the
    global row-permutation sidecar (:func:`reassemble_global`) --
    cross-tier resume (dist -> single-device/host and back) falls out
    of the same path."""

    path: str | None = None
    every: int = 0
    resume: "SolverSnapshot | None" = None
    secs: float = 0.0
    repartition: bool = False

    def __post_init__(self):
        if self.every > 0 and self.secs > 0:
            raise ValueError("checkpoint cadence is EITHER ckpt_every "
                             "K iterations OR ckpt_secs S wall-clock "
                             "seconds, not both")
        if self.secs < 0:
            raise ValueError("ckpt_secs must be positive seconds")
        if self.path is not None and self.every <= 0 and self.secs <= 0:
            raise ValueError("checkpointing needs a snapshot cadence "
                             "(ckpt_every K or ckpt_secs S)")
        if self.path is None and self.resume is None:
            raise ValueError("a CheckpointConfig needs a snapshot path "
                             "and/or a snapshot to resume from")
        if self.repartition and self.resume is None:
            raise ValueError("repartition is a resume policy; it needs "
                             "a snapshot to resume from")

    # chunk length of the first dispatch under a wall-clock cadence,
    # before any s/iteration measurement exists (small, so the probe
    # costs at most one early snapshot)
    PROBE_CHUNK = 16

    def chunk_for(self, s_per_iter: float | None) -> int:
        """The next dispatch's chunk length: the iteration period when
        one is set; under a wall-clock cadence, ``secs`` divided by the
        measured seconds/iteration (a probe chunk until one exists);
        unbounded for resume-only configurations -- one final chunk to
        convergence."""
        if self.every > 0:
            return self.every
        if self.secs > 0:
            if not s_per_iter or s_per_iter <= 0:
                return self.PROBE_CHUNK
            return max(1, min(int(self.secs / s_per_iter) or 1, 1 << 24))
        return 1 << 30


@dataclasses.dataclass
class SolverSnapshot:
    """One loaded snapshot: validated metadata + named host arrays."""

    meta: dict
    arrays: dict

    @property
    def iteration(self) -> int:
        return int(self.meta["iteration"])


# the carry leaves that are psum'd scalars (mesh tiers: replicated,
# not sharded) -- everything else is a per-part vector
SCALAR_LEAVES = frozenset({"gamma", "alpha", "rr"})


def carry_names(pipelined: bool, precond: bool) -> tuple:
    """The canonical order of the loop-carry leaves a snapshot stores
    (x first, then the recurrence vectors, then the scalars) -- ONE
    layout shared by the snapshot writer, the resume reconstruction,
    and every tier's ``state_io`` program outputs, so the single- and
    multi-part tiers' snapshots stay field-compatible."""
    if not pipelined:
        names = ("x", "r", "p", "gamma")
        return names + (("rr",) if precond else ())
    if precond:
        return ("x", "r", "u", "w", "p", "s", "q", "z",
                "gamma", "alpha", "rr")
    return ("x", "r", "w", "p", "t", "z", "gamma", "alpha")


def ca_carry_names(kind: str) -> tuple:
    """Loop-carry leaves of the COMMUNICATION-AVOIDING recurrences
    (ROADMAP item 4c).  ``sstep``: at a block boundary the s-step
    state is exactly classic-shaped -- the basis and Gram products
    are rebuilt from ``(r, p)`` at every block start, so nothing else
    survives the boundary and the snapshot layout matches classic CG's
    (block-boundary-aligned cadence is the solver's job).  ``pl``: the
    deep pipeline has no classic-shaped boundary, so the snapshot
    carries its WHOLE working set -- the z-window ``Z``/``V``, the
    Gram column ``zzq``, the pending products ``gb``, the scalar
    histories ``gammas``/``deltas``, and the ABSOLUTE pipeline
    counters ``j``/``adv``."""
    if kind == "sstep":
        return ("x", "r", "p", "gamma")
    return ("x", "q", "dprev", "ptilde", "Z", "V", "zzq", "gb",
            "gammas", "deltas", "j", "adv")


# the batched tier's per-RHS carry leaves that are (B,)-shaped column
# vectors rather than per-row vectors: replicated on the mesh tiers
# (like the psum'd scalars), passed through untouched by repartition
BATCHED_COL_LEAVES = frozenset({"gamma", "rr", "done", "iters"})


def batched_carry_names(precond: bool) -> tuple:
    """Loop-carry leaves of the BATCHED classic recurrence
    (acg_tpu.solvers.batched): x/r/p are (n, B) column blocks --
    per-RHS leaves, one column per right-hand side -- and
    gamma[/rr]/done/iters are (B,) per-RHS vectors.  A snapshot of
    this layout is what lets a whole BATCH survive preemption with
    every RHS's progress (including already-frozen columns) intact."""
    names = ("x", "r", "p", "gamma")
    if precond:
        names = names + ("rr",)
    return names + ("done", "iters")


# tiers whose carry leaves are field-compatible global row vectors
# once reassembled (carry_names is shared): the repartition-resume set.
# sharded-dia pads rows to the mesh and is excluded -- its vectors are
# not plain global row order.  The batched tiers repartition among
# themselves (their leaves carry a trailing per-RHS axis).
REPARTITION_TIERS = frozenset({"jax-cg", "dist-cg", "host-cg"})
BATCHED_REPARTITION_TIERS = frozenset({"jax-cg-batched",
                                       "dist-cg-batched"})


def _crc(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def env_meta() -> dict:
    """The runtime environment a snapshot was written under
    (jax/jaxlib versions + backend platform): a resume across a
    version or backend change is numerically legal but can perturb the
    trajectory, so :func:`check_resume_env` warns instead of silently
    continuing."""
    meta = {}
    try:
        import jax
        import jaxlib

        meta["jax"] = str(jax.__version__)
        meta["jaxlib"] = str(jaxlib.__version__)
        try:
            meta["backend"] = str(jax.default_backend())
        except Exception:  # noqa: BLE001 -- backend down: still record
            meta["backend"] = None  # the versions
    except Exception:  # noqa: BLE001 -- no jax (host-only callers)
        pass
    return meta


def check_resume_env(snap: SolverSnapshot, stats=None) -> list:
    """Compare the snapshot's recorded environment against this
    process's; mismatches WARN (stderr + a structured
    ``resume-env-mismatch`` event on ``stats``) instead of refusing --
    the resume is legal, but a changed jax/jaxlib/backend can shift
    rounding enough to move the iteration count, and the operator
    should know why.  Returns the mismatch descriptions ([] when clean
    or when the snapshot predates env recording)."""
    import sys

    recorded = snap.meta.get("env") or {}
    if not recorded:
        return []
    here = env_meta()
    mismatches = [
        f"{key} {recorded.get(key)!r} -> {here.get(key)!r}"
        for key in ("jax", "jaxlib", "backend")
        if key in recorded and key in here
        and recorded.get(key) != here.get(key)]
    if mismatches:
        detail = ", ".join(mismatches)
        sys.stderr.write(
            f"acg-tpu: warning: resuming across an environment change "
            f"({detail}); the trajectory may deviate from the "
            f"pre-crash run's\n")
        if stats is not None:
            from acg_tpu.telemetry import record_event
            record_event(stats, "resume-env-mismatch", detail)
    return mismatches


def vector_checksum(v) -> int:
    """CRC32 of a host vector's bytes -- stored for ``b`` so a resume
    against a different right-hand side refuses instead of silently
    continuing somebody else's solve."""
    return _crc(np.ascontiguousarray(np.asarray(v)).tobytes())


def save_snapshot(path, meta: dict, arrays: dict) -> int:
    """Write one snapshot atomically; returns the byte size.

    Layout: ``MAGIC`` + one header line
    ``{version, header_crc, payload_crc, header_len}`` + the JSON
    header (meta + per-array manifest) + the raw little-endian array
    payload.  The file lands under a temporary name and is
    ``os.replace``d into place, so a crash mid-write can never leave a
    torn snapshot where a good one stood.

    The writer stamps the runtime environment (:func:`env_meta`) into
    the metadata so ``--resume`` across a jax/jaxlib/backend change
    can warn (:func:`check_resume_env`)."""
    meta = dict(meta)
    meta.setdefault("env", env_meta())
    manifest = []
    blobs = []
    off = 0
    for name, arr in arrays.items():
        a = np.asarray(arr)
        # record the shape BEFORE ascontiguousarray: it promotes 0-d
        # scalars (the carried gamma/alpha/rr) to shape (1,), which
        # would resume a scalar as a 1-vector and break the loop carry
        shape = list(a.shape)
        raw = np.ascontiguousarray(a).tobytes()
        manifest.append({"name": str(name), "dtype": str(a.dtype),
                         "shape": shape, "offset": off,
                         "nbytes": len(raw)})
        blobs.append(raw)
        off += len(raw)
    payload = b"".join(blobs)
    header = json.dumps({"meta": meta, "arrays": manifest},
                        sort_keys=True).encode("utf-8")
    preamble = json.dumps({"version": VERSION,
                           "header_crc": _crc(header),
                           "payload_crc": _crc(payload),
                           "header_len": len(header)}).encode("utf-8")
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(MAGIC)
            f.write(preamble + b"\n")
            f.write(header)
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
    # live-observatory tier: a committed snapshot is status evidence
    # (the operator's "how stale would a resume be" question; no-op
    # disarmed)
    from acg_tpu import observatory
    observatory.note_event(
        "snapshot", f"seq {meta.get('seq', '?')} committed at "
                    f"iteration {meta.get('iteration', '?')}")
    return len(MAGIC) + len(preamble) + 1 + len(header) + len(payload)


def load_snapshot(path) -> SolverSnapshot:
    """Read + verify one snapshot; raises a typed
    :class:`~acg_tpu.errors.AcgError` on any integrity failure (bad
    magic, unknown version, header or payload checksum mismatch,
    truncation) -- a resumed solve must never start from garbage."""
    def bad(why: str):
        return AcgError(ErrorCode.INVALID_VALUE,
                        f"{path}: not a usable snapshot ({why})")

    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        raise AcgError(ErrorCode.INVALID_VALUE, f"{path}: {e}")
    if not blob.startswith(MAGIC):
        raise bad("bad magic; not an acg-tpu snapshot")
    rest = blob[len(MAGIC):]
    nl = rest.find(b"\n")
    if nl < 0:
        raise bad("truncated preamble")
    try:
        pre = json.loads(rest[:nl])
    except ValueError:
        raise bad("unparseable preamble")
    if int(pre.get("version", -1)) != VERSION:
        raise bad(f"unknown snapshot version {pre.get('version')!r}")
    hlen = int(pre["header_len"])
    header = rest[nl + 1: nl + 1 + hlen]
    payload = rest[nl + 1 + hlen:]
    if len(header) != hlen:
        raise bad("truncated header")
    if _crc(header) != int(pre["header_crc"]):
        raise bad("header checksum mismatch")
    if _crc(payload) != int(pre["payload_crc"]):
        raise bad("payload checksum mismatch")
    doc = json.loads(header)
    arrays = {}
    for m in doc["arrays"]:
        start, n = int(m["offset"]), int(m["nbytes"])
        raw = payload[start: start + n]
        if len(raw) != n:
            raise bad(f"array {m['name']!r} truncated")
        arrays[m["name"]] = np.frombuffer(
            raw, dtype=np.dtype(m["dtype"])).reshape(m["shape"]).copy()
    return SolverSnapshot(meta=doc["meta"], arrays=arrays)


def validate_resume(snap: SolverSnapshot, *, tier: str, pipelined: bool,
                    precond: str | None, n: int, dtype,
                    b_crc: int | None = None,
                    nparts: int | None = None,
                    repartition: bool = False,
                    nrhs: int | None = None,
                    algorithm: str | None = None) -> None:
    """Refuse a snapshot that does not describe THIS solve: wrong tier,
    algorithm, preconditioner, size, dtype, partition count, or
    right-hand side.  A mismatch here means the operator pointed
    ``--resume`` at somebody else's solve -- continuing would converge
    to the wrong answer with a green exit code.

    ``repartition=True`` (the ``--resume-repartition`` opt-in) relaxes
    EXACTLY the shape checks -- tier and partition count -- for the
    tiers whose reassembled carries are field-compatible
    (:data:`REPARTITION_TIERS`): an N-part snapshot may then restore
    onto an M-part mesh, the single-device tier, or the host oracle.
    Algorithm, preconditioner, size, dtype and right-hand-side
    mismatches keep refusing -- those would still converge to the
    wrong answer."""
    m = snap.meta

    def need(key, want, what):
        got = m.get(key)
        if got != want:
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                f"snapshot does not match this solve: {what} is "
                f"{got!r}, this run has {want!r}")

    if repartition:
        got_tier = m.get("tier")
        # batched tiers repartition among themselves: their carry
        # leaves carry a trailing per-RHS axis the single-RHS tiers'
        # reconstruction cannot consume (and vice versa)
        allowed = (BATCHED_REPARTITION_TIERS if nrhs is not None
                   else REPARTITION_TIERS)
        if tier not in allowed or got_tier not in allowed:
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                f"repartition resume supports the "
                f"{'/'.join(sorted(allowed))} tiers; this "
                f"snapshot is {got_tier!r} and this solve "
                f"{tier!r}")
    else:
        need("tier", tier, "solver tier")
        if nparts is not None:
            need("nparts", int(nparts), "partition count")
    need("pipelined", bool(pipelined), "algorithm (pipelined)")
    if algorithm is not None or m.get("algorithm") is not None:
        # communication-avoiding recurrences snapshot a DIFFERENT carry
        # layout per recurrence (ca_carry_names): an sstep:4 snapshot
        # resumed as pipelined:3 (or classic) would scramble the state
        need("algorithm", algorithm, "recurrence")
    need("precond", precond, "preconditioner")
    need("n", int(n), "unknowns")
    need("dtype", str(np.dtype(dtype)), "vector dtype")
    if nrhs is not None:
        # a batch must resume as the SAME batch: per-RHS leaves of a
        # different width would scramble every column's Krylov state
        need("nrhs", int(nrhs), "right-hand-side count")
    if b_crc is not None and m.get("b_crc") is not None:
        need("b_crc", int(b_crc), "right-hand-side checksum")


def reassemble_global(snap: SolverSnapshot) -> SolverSnapshot:
    """An N-part snapshot's carry vectors reassembled into GLOBAL row
    order via the stored row-permutation sidecar (``_rowperm`` array +
    ``part_rows`` metadata), ready to re-slice onto any partition --
    the shape-portable half of ``--resume-repartition``.  Snapshots
    from the single-device/host tiers (no sidecar, nparts absent or 1)
    already store global vectors and pass through unchanged.  A
    missing, malformed or corrupted sidecar REFUSES with a typed
    error: scattering rows through a wrong permutation would resume a
    scrambled Krylov state and converge to a wrong answer."""
    m = snap.meta
    nparts = int(m.get("nparts") or 1)
    if nparts <= 1 and "_rowperm" not in snap.arrays:
        return snap

    def bad(why: str):
        return AcgError(
            ErrorCode.INVALID_VALUE,
            f"snapshot cannot be repartitioned: {why}")

    n = int(m["n"])
    perm = snap.arrays.get("_rowperm")
    part_rows = m.get("part_rows")
    if perm is None or part_rows is None:
        raise bad("it lacks the row-permutation sidecar (_rowperm + "
                  "part_rows; written by checkpoint-armed distributed "
                  "solves from this release on) -- re-snapshot, or "
                  "resume on the matching partition without "
                  "--resume-repartition")
    perm = np.asarray(perm).reshape(-1).astype(np.int64, copy=False)
    try:
        part_rows = [int(r) for r in part_rows]
    except (TypeError, ValueError):
        raise bad(f"part_rows is not a row-count list: {part_rows!r}")
    if len(part_rows) != nparts or any(r < 0 for r in part_rows) \
            or sum(part_rows) != n:
        raise bad(f"part_rows {part_rows!r} does not partition "
                  f"{n} rows into {nparts} parts")
    from acg_tpu.partition import is_permutation
    if not is_permutation(perm, n):
        raise bad(f"the row-permutation sidecar is not a permutation "
                  f"of {n} rows (corrupted or stale sidecar)")

    batched = int(m.get("nrhs") or 0) > 1
    arrays = {}
    for name, a in snap.arrays.items():
        if name == "_rowperm":
            continue
        a = np.asarray(a)
        if name in SCALAR_LEAVES or a.ndim == 0 \
                or (batched and name in BATCHED_COL_LEAVES):
            # per-RHS column vectors (gamma/done/iters of the batched
            # carry) are replicated, not row-partitioned: pass through
            arrays[name] = a
            continue
        if batched:
            # batched per-RHS leaves stack as (nparts, pad, B): the
            # row permutation applies to axis 1, columns ride along
            if a.ndim != 3 or a.shape[0] != nparts \
                    or a.shape[1] < max(part_rows, default=0):
                raise bad(f"carry leaf {name!r} (shape {a.shape}) "
                          f"does not hold the {nparts}-part batched "
                          f"stacked layout")
            out = np.zeros((n, a.shape[2]), dtype=a.dtype)
            off = 0
            for p, rows in enumerate(part_rows):
                out[perm[off: off + rows]] = a[p, :rows]
                off += rows
            arrays[name] = out
            continue
        if a.ndim != 2 or a.shape[0] != nparts \
                or a.shape[1] < max(part_rows, default=0):
            raise bad(f"carry leaf {name!r} (shape {a.shape}) does "
                      f"not hold the {nparts}-part stacked layout")
        out = np.zeros(n, dtype=a.dtype)
        off = 0
        for p, rows in enumerate(part_rows):
            out[perm[off: off + rows]] = a[p, :rows]
            off += rows
        arrays[name] = out
    meta = dict(m)
    meta["repartitioned_from"] = {"tier": m.get("tier"),
                                  "nparts": nparts}
    meta.pop("nparts", None)
    meta.pop("part_rows", None)
    return SolverSnapshot(meta=meta, arrays=arrays)


def apply_repartition(snap: SolverSnapshot, *, tier: str, nparts: int,
                      stats, precond_spec=None) -> tuple:
    """The shared repartition-resume sequence (ONE implementation for
    the jax-cg / dist-cg / host-cg chunk drivers): reassemble the
    snapshot's carry into global row order, and when the source shape
    differs from this solve's, record the repartition metric + the
    structured event and warn when the preconditioner operator depends
    on the partition (continuing under a different M is flexible-CG).
    Returns ``(snapshot, repartitioned)`` -- ``repartitioned`` is
    ``{"tier", "nparts"}`` of the source, or None when the shapes
    already matched."""
    import sys

    src = (snap.meta.get("tier"), int(snap.meta.get("nparts") or 1))
    snap = reassemble_global(snap)
    if src == (tier, int(nparts)):
        return snap, None
    from acg_tpu import metrics
    from acg_tpu.telemetry import record_event

    metrics.record_repartition()
    record_event(stats, "repartition",
                 f"resumed a {src[1]}-part {src[0]} snapshot on "
                 f"{int(nparts)}-part {tier}")
    from acg_tpu.precond import partition_sensitive
    if precond_spec is not None and partition_sensitive(precond_spec):
        sys.stderr.write(
            f"acg-tpu: warning: --precond {precond_spec} depends on "
            f"the partition; the repartitioned resume continues with "
            f"a DIFFERENT M (flexible-CG semantics -- expect a few "
            f"extra iterations)\n")
    return snap, {"tier": src[0], "nparts": src[1]}


def agree_seq(seq: int, iteration: int, timeout: float = 120.0) -> None:
    """Multi-controller snapshot commit barrier: every controller
    reports its (sequence, iteration) pair and all verify the pod holds
    ONE agreed state before the primary writes -- a snapshot whose
    ranks disagree on the iteration number is corruption with a valid
    checksum.  Single-process: free."""
    import jax

    if jax.process_count() == 1:
        return
    from acg_tpu.parallel.erragree import allgather_blobs

    mine = f"{int(seq)}:{int(iteration)}"
    got = allgather_blobs(mine, tag="ckpt-seq", timeout=timeout)
    if any(g != mine for g in got):
        raise AcgError(
            ErrorCode.INVALID_VALUE,
            f"snapshot sequence disagreement across controllers: "
            f"{sorted(set(got))} (mine {mine}) -- refusing to commit")


def trace_tail(trace, n: int = 8) -> list:
    """The trailing telemetry-ring rows as small JSON-able dicts (the
    snapshot's post-mortem evidence; [] without a trace)."""
    if trace is None:
        return []
    m = min(int(n), trace.iterations.size)
    return [trace.record_dict(trace.iterations.size - m + i)
            for i in range(m)]
