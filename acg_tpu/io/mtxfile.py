"""Matrix Market file I/O (text, gzip, and raw binary).

Rebuilds the role of the reference's ``acg/mtxfile.c`` (5154 LoC, SURVEY.md
component #1): reading/writing ``.mtx`` files in text form, gzip-compressed
text, and a fast raw-binary form whose data section is the concatenation of
the row-index array, the column-index array, and the value array
(``mtxfile.c:1492-1497``: ``fwrite(rowidx); fwrite(colidx); fwrite(vals)``
with ``acgidx_t`` = int64 and 1-based indices, following the text header and
size line unchanged).  Binary files written here are record-compatible with
the reference's ``mtx2bin`` output at ``IDXSIZE=64``.

Unlike the reference, parsing is vectorised (numpy's C tokenizer) rather
than a per-line ``parse_acgidx_t`` loop (``mtxfile.c:706-728``); an optional
native C++ fast path lives in ``acg_tpu._native``.  The MPI scatter/gather
of file chunks (``mtxfile.h:997-1087``) has no equivalent here because the
TPU build is single-controller: one host reads, the mesh shards.
"""

from __future__ import annotations

import dataclasses
import gzip
import os

import numpy as np

from acg_tpu.errors import AcgError, ErrorCode

_VALID_OBJECTS = ("matrix", "vector")
_VALID_FORMATS = ("coordinate", "array")
_VALID_FIELDS = ("real", "double", "integer", "pattern")
_VALID_SYMMETRIES = ("general", "symmetric", "skew-symmetric", "hermitian")

IDX_DTYPE = np.int64  # matches reference acgidx_t at IDXSIZE=64 (config.h:59-95)


@dataclasses.dataclass
class MtxFile:
    """An in-memory Matrix Market file.

    Indices are stored 0-based internally; text/binary files on disk are
    1-based as mandated by the format.  ``vals`` is None for ``pattern``
    fields.  For ``format == "array"`` (dense), ``rowidx``/``colidx`` are
    None and ``vals`` holds the column-major entries.
    """

    object: str = "matrix"
    format: str = "coordinate"
    field: str = "real"
    symmetry: str = "general"
    nrows: int = 0
    ncols: int = 0
    nnz: int = 0
    rowidx: np.ndarray | None = None
    colidx: np.ndarray | None = None
    vals: np.ndarray | None = None
    comments: list[str] = dataclasses.field(default_factory=list)

    def __post_init__(self):
        if self.object not in _VALID_OBJECTS:
            raise AcgError(ErrorCode.INVALID_VALUE, f"object {self.object!r}")
        if self.format not in _VALID_FORMATS:
            raise AcgError(ErrorCode.INVALID_VALUE, f"format {self.format!r}")
        if self.field not in _VALID_FIELDS:
            raise AcgError(ErrorCode.INVALID_VALUE, f"field {self.field!r}")
        if self.symmetry not in _VALID_SYMMETRIES:
            raise AcgError(ErrorCode.INVALID_VALUE, f"symmetry {self.symmetry!r}")

    @property
    def is_symmetric(self) -> bool:
        return self.symmetry == "symmetric"

    def to_coo(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Return (rowidx, colidx, vals) as 0-based COO triplets.

        Pattern matrices get unit values.  Symmetry is NOT expanded here;
        see :func:`expand_symmetry`.
        """
        if self.format != "coordinate":
            raise AcgError(ErrorCode.NOT_SUPPORTED, "to_coo on array format")
        vals = self.vals
        if vals is None:
            vals = np.ones(self.nnz, dtype=np.float64)
        return self.rowidx, self.colidx, vals


def expand_symmetry(rowidx, colidx, vals, nrows=None):
    """Expand one-triangle symmetric COO into full COO (both triangles)."""
    offdiag = rowidx != colidx
    r2 = np.concatenate([rowidx, colidx[offdiag]])
    c2 = np.concatenate([colidx, rowidx[offdiag]])
    v2 = np.concatenate([vals, vals[offdiag]])
    return r2, c2, v2


def _open_maybe_gzip(path, mode="rb"):
    if isinstance(path, (str, os.PathLike)):
        f = open(path, mode)
        magic = f.read(2)
        f.seek(0)
        if magic == b"\x1f\x8b":
            return gzip.open(f, mode)
        return f
    return path


def _parse_header_line(line: str) -> tuple[str, str, str, str]:
    parts = line.strip().split()
    if len(parts) < 5 or parts[0] != "%%MatrixMarket":
        raise AcgError(ErrorCode.INVALID_FORMAT, f"bad header: {line.strip()!r}")
    obj, fmt, field, sym = (p.lower() for p in parts[1:5])
    if field == "double":
        field = "real"
    return obj, fmt, field, sym


def read_mtx(path, binary: bool = False, layout_hint: str | None = None) -> MtxFile:
    """Read a Matrix Market file (text, gzipped text, or raw binary).

    Equivalent of ``acgmtxfile_read/fread/gzread`` (``mtxfile.h:352-416``).
    ``binary`` selects the raw data section layout (the reference's
    ``--binary`` flag); gzip is auto-detected from the magic bytes.
    """
    f = _open_maybe_gzip(path, "rb")
    try:
        return _read_mtx_stream(f, binary)
    finally:
        if isinstance(path, (str, os.PathLike)):
            f.close()



def _read_header_meta(f):
    """Parse header line, comments, and size line from an open binary
    stream; returns (obj, fmt, field, sym, comments, nrows, ncols, nnz)
    with the stream positioned at the data section."""
    header = f.readline().decode("ascii", errors="replace")
    obj, fmt, field, sym = _parse_header_line(header)
    comments = []
    line = f.readline()
    while line.startswith(b"%"):
        comments.append(line.decode("utf-8", errors="replace").rstrip("\n"))
        line = f.readline()
    parts = line.split()
    if fmt == "coordinate":
        if len(parts) != 3:
            raise AcgError(ErrorCode.INVALID_FORMAT,
                           f"bad size line: {line!r}")
        nrows, ncols, nnz = (int(s) for s in parts)
    else:
        if obj == "vector" and len(parts) == 1:
            nrows, ncols = int(parts[0]), 1
        elif len(parts) == 2:
            nrows, ncols = int(parts[0]), int(parts[1])
        else:
            raise AcgError(ErrorCode.INVALID_FORMAT,
                           f"bad size line: {line!r}")
        nnz = nrows * ncols
    return obj, fmt, field, sym, comments, nrows, ncols, nnz


def _read_mtx_stream(f, binary: bool) -> MtxFile:
    obj, fmt, field, sym, comments, nrows, ncols, nnz = _read_header_meta(f)

    rowidx = colidx = vals = None
    if fmt == "coordinate":
        if binary:
            rowidx = np.frombuffer(f.read(8 * nnz), dtype=IDX_DTYPE).copy()
            if rowidx.size != nnz:
                raise AcgError(ErrorCode.EOF, "binary rowidx truncated")
            colidx = np.frombuffer(f.read(8 * nnz), dtype=IDX_DTYPE).copy()
            if colidx.size != nnz:
                raise AcgError(ErrorCode.EOF, "binary colidx truncated")
            rowidx -= 1
            colidx -= 1
            if field != "pattern":
                vdt = np.float64 if field == "real" else np.int32
                vals = np.frombuffer(f.read(np.dtype(vdt).itemsize * nnz), dtype=vdt).copy()
                if vals.size != nnz:
                    raise AcgError(ErrorCode.EOF, "binary vals truncated")
            if nnz > 0 and (rowidx.min() < 0 or rowidx.max() >= nrows
                            or colidx.min() < 0 or colidx.max() >= ncols):
                raise AcgError(ErrorCode.INDEX_OUT_OF_BOUNDS,
                               "mtx indices out of range")
        else:
            from acg_tpu import _native
            if _native.available() and nnz > 0:
                try:
                    rowidx, colidx, vals = _native.parse_coord(
                        f.read(), nnz, nrows, ncols, field != "pattern")
                except _native.NativeParseError as e:
                    code = {-2: ErrorCode.EOF,
                            -3: ErrorCode.INDEX_OUT_OF_BOUNDS}.get(
                        e.code, ErrorCode.INVALID_FORMAT)
                    raise AcgError(code, "bad coordinate data section")
                if field == "integer":
                    vals = vals.astype(np.int32)
            else:
                ncolumns = 2 if field == "pattern" else 3
                data = np.loadtxt(f, dtype=np.float64, ndmin=2, max_rows=nnz) if nnz > 0 else np.zeros((0, ncolumns))
                if data.shape[0] != nnz or (nnz > 0 and data.shape[1] < ncolumns):
                    raise AcgError(ErrorCode.INVALID_FORMAT, f"expected {nnz} x {ncolumns} data entries, got {data.shape}")
                rowidx = data[:, 0].astype(IDX_DTYPE) - 1
                colidx = data[:, 1].astype(IDX_DTYPE) - 1
                if field == "real":
                    vals = np.ascontiguousarray(data[:, 2])
                elif field == "integer":
                    vals = data[:, 2].astype(np.int32)
                # (the native parser bounds-checks inline)
                if nnz > 0 and (rowidx.min() < 0 or rowidx.max() >= nrows
                                or colidx.min() < 0 or colidx.max() >= ncols):
                    raise AcgError(ErrorCode.INDEX_OUT_OF_BOUNDS,
                                   "mtx indices out of range")
    else:  # array
        if binary:
            vdt = np.float64 if field == "real" else np.int32
            vals = np.frombuffer(f.read(np.dtype(vdt).itemsize * nnz), dtype=vdt).copy()
            if vals.size != nnz:
                raise AcgError(ErrorCode.EOF, "binary array vals truncated")
        else:
            from acg_tpu import _native
            if _native.available() and nnz > 0:
                try:
                    vals = _native.parse_array(f.read(), nnz)
                except _native.NativeParseError as e:
                    code = ErrorCode.EOF if e.code == -2 else ErrorCode.INVALID_FORMAT
                    raise AcgError(code, "bad array data section")
            else:
                vals = np.loadtxt(f, dtype=np.float64, ndmin=1, max_rows=nnz).reshape(-1)
                if vals.size != nnz:
                    raise AcgError(ErrorCode.INVALID_FORMAT, f"expected {nnz} array entries, got {vals.size}")
            if field == "integer":
                vals = vals.astype(np.int32)

    return MtxFile(object=obj, format=fmt, field=field, symmetry=sym,
                   nrows=nrows, ncols=ncols, nnz=nnz,
                   rowidx=rowidx, colidx=colidx, vals=vals, comments=comments)


def _rowcol_argsort(r: np.ndarray, c: np.ndarray,
                    ncols: int) -> np.ndarray:
    """Stable argsort by (row, col) -- the hot host operation of the
    offline expand/permute tools (O(nnz log nnz) over ~1e9 entries at
    512^3 scale).  Uses the native int64 radix argsort
    (``native/src/sort.cpp``) on the fused key ``row * ncols + col``
    when the key fits int64; numpy lexsort otherwise."""
    from acg_tpu import _native

    r = np.asarray(r)
    c = np.asarray(c)
    # the fused key is only collision-free when every column index is
    # strictly below the stride (callers may pass permuted indices up
    # to nrows-1 on rectangular files -- guard, don't assume)
    if _native.available() and r.size:
        stride = max(int(ncols), int(c.max(initial=0)) + 1)
        if int(r.max(initial=0) + 1) * stride < 2 ** 63:
            key = r.astype(np.int64) * np.int64(stride) + c.astype(np.int64)
            return _native.argsort(key)
    return np.lexsort((c, r))


def expand_to_rowsorted_full(mtx: MtxFile) -> MtxFile:
    """Expand one-triangle symmetric storage to FULL storage with entries
    sorted by (row, col), symmetry declared ``general``.

    This is the offline preprocessing step (``mtx2bin --expand``) that
    makes a binary file RANGE-READABLE: with full storage, every entry of
    row i lives in row i's contiguous span, so a controller can read
    exactly its rows (:func:`read_mtx_row_range`) -- one-triangle files
    scatter row i's upper entries into other rows' spans."""
    if mtx.symmetry not in ("general", "symmetric"):
        raise AcgError(ErrorCode.NOT_SUPPORTED,
                       f"cannot expand {mtx.symmetry!r} storage (only "
                       f"general/symmetric)")
    r, c, v = mtx.to_coo()
    if mtx.symmetry == "symmetric":
        r, c, v = expand_symmetry(r, c, v, mtx.nrows)
    order = _rowcol_argsort(r, c, mtx.ncols)
    return MtxFile(object=mtx.object, format=mtx.format, field=mtx.field,
                   symmetry="general", nrows=mtx.nrows, ncols=mtx.ncols,
                   nnz=int(r.size), rowidx=r[order], colidx=c[order],
                   vals=None if v is None else np.asarray(v)[order],
                   comments=list(mtx.comments))


def apply_partition_rowsorted(mtx: MtxFile, part: np.ndarray):
    """Symmetrically permute FULL-storage ``mtx`` so each partition's
    rows are CONTIGUOUS: rows grouped by part id (stable -- natural
    order within a part), columns renumbered by the same permutation
    (P A P^T), entries re-sorted by (row, col).

    This is what lets an arbitrary (METIS/graph) partition ride the
    band-partition range-read machinery unchanged: after grouping,
    part p owns rows ``[bounds[p], bounds[p+1])`` of the permuted
    matrix, so :func:`read_mtx_row_range` +
    ``graph.subdomain_from_row_slice`` (which is fully general in
    column connectivity) reconstruct exactly the partition METIS chose.
    The role of the reference's partition/permute/compact of matrix
    files (``acgmtxfilepartition``, ``mtxfile.h:436,1450``) restated
    for rootless range reads.

    Returns ``(permuted, bounds, perm)``: ``bounds`` has nparts+1
    ascending row boundaries and ``perm[new] = old`` maps permuted row
    ids back to the input ordering (apply to solutions as
    ``x_orig[perm] = x_perm``).
    """
    if mtx.symmetry != "general":
        raise AcgError(ErrorCode.NOT_SUPPORTED,
                       "apply_partition_rowsorted needs FULL storage "
                       "(expand first)")
    part = np.asarray(part)
    if part.size != mtx.nrows:
        raise AcgError(ErrorCode.INVALID_VALUE,
                       f"partition vector has {part.size} entries, "
                       f"matrix has {mtx.nrows} rows")
    nparts = int(part.max()) + 1 if part.size else 0
    if part.size and part.min() < 0:
        raise AcgError(ErrorCode.INVALID_VALUE, "negative part id")
    perm = np.argsort(part, kind="stable").astype(np.int64)
    rank = np.empty_like(perm)
    rank[perm] = np.arange(perm.size, dtype=np.int64)
    counts = np.bincount(part, minlength=nparts)
    bounds = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)

    r, c, v = mtx.to_coo()
    nr, nc = rank[np.asarray(r)], rank[np.asarray(c)]
    order = _rowcol_argsort(nr, nc, mtx.ncols)
    permuted = MtxFile(object=mtx.object, format=mtx.format,
                       field=mtx.field, symmetry="general",
                       nrows=mtx.nrows, ncols=mtx.ncols, nnz=int(nr.size),
                       rowidx=nr[order], colidx=nc[order],
                       vals=None if v is None else np.asarray(v)[order],
                       comments=list(mtx.comments))
    return permuted, bounds, perm


def read_mtx_sizes(path) -> tuple[int, int, int]:
    """(nrows, ncols, nnz) from a Matrix Market header without reading
    the data section (O(1) I/O; used to derive band bounds before a
    range read)."""
    with _open_maybe_gzip(path, "rb") as f:
        _, _, _, _, _, nrows, ncols, nnz = _read_header_meta(f)
        return nrows, ncols, nnz


def read_mtx_row_range(path, row_lo: int, row_hi: int) -> MtxFile:
    """Read ONLY the entries with ``row_lo <= row < row_hi`` from a
    row-sorted BINARY coordinate file (``mtx2bin --expand`` output).

    The pod-scale ingest primitive (the role of the reference's
    root-read + ``acgmtxfile_scatterv``, ``mtxfile.h:997-1087``, without
    the root): the row span is located by BISECTION over the on-disk
    rowidx array (O(log nnz) 8-byte seeks), then exactly the three
    slices are read -- I/O and memory are O(local nnz), not O(nnz).
    Returns an :class:`MtxFile` with global ``nrows/ncols`` and the
    local ``nnz``; monotonicity of the slice is verified.
    """
    if not (0 <= row_lo <= row_hi):
        raise AcgError(ErrorCode.INVALID_VALUE,
                       f"bad row range [{row_lo}, {row_hi})")
    with open(path, "rb") as f:
        obj, fmt, field, sym, comments, nrows, ncols, nnz = \
            _read_header_meta(f)
        if fmt != "coordinate":
            raise AcgError(ErrorCode.INVALID_FORMAT,
                           "row-range reads need a coordinate file")
        data_off = f.tell()

        idx_sz = np.dtype(IDX_DTYPE).itemsize

        # probe that the data section IS binary before bisecting over it
        # (read_mtx takes an explicit ``binary`` flag; this reader has no
        # flag, and frombuffer over an ASCII data section would otherwise
        # fail with a misleading "not row-sorted" error, or worse, pass):
        # the binary layout's size is fully determined by the header
        # (rowidx, colidx, vals as consecutive raw arrays), and entry 0's
        # 1-based rowidx must be a plausible row number.
        val_sz = 0 if field == "pattern" else \
            (8 if field == "real" else 4)
        f.seek(0, os.SEEK_END)
        if f.tell() != data_off + nnz * (2 * idx_sz + val_sz):
            raise AcgError(ErrorCode.INVALID_FORMAT,
                           f"{path}: data section size does not match the "
                           f"binary layout for {nnz} entries -- not a "
                           f"binary file? (convert with mtx2bin --expand)")
        if nnz:
            f.seek(data_off)
            first = int(np.frombuffer(f.read(idx_sz), dtype=IDX_DTYPE)[0])
            if not (1 <= first <= nrows):
                raise AcgError(ErrorCode.INVALID_FORMAT,
                               f"{path}: first rowidx {first} out of range "
                               f"-- not a binary coordinate file?")

        def row_at(k: int) -> int:
            f.seek(data_off + idx_sz * k)
            buf = f.read(idx_sz)
            if len(buf) != idx_sz:
                raise AcgError(ErrorCode.EOF, "binary rowidx truncated")
            return int(np.frombuffer(buf, dtype=IDX_DTYPE)[0]) - 1

        def lower_bound(row: int) -> int:
            """First k with rowidx[k] >= row (file is row-sorted)."""
            lo, hi = 0, nnz
            while lo < hi:
                mid = (lo + hi) // 2
                if row_at(mid) < row:
                    lo = mid + 1
                else:
                    hi = mid
            return lo

        k0 = lower_bound(row_lo)
        k1 = lower_bound(row_hi)
        cnt = k1 - k0

        def read_block(block: int, dtype, item: int) -> np.ndarray:
            f.seek(data_off + block + item * k0)
            buf = f.read(item * cnt)
            if len(buf) != item * cnt:
                raise AcgError(ErrorCode.EOF, "binary data truncated")
            return np.frombuffer(buf, dtype=dtype).copy()

        rowidx = read_block(0, IDX_DTYPE, idx_sz) - 1
        colidx = read_block(idx_sz * nnz, IDX_DTYPE, idx_sz) - 1
        vals = None
        if field != "pattern":
            vdt = np.float64 if field == "real" else np.int32
            vals = read_block(2 * idx_sz * nnz, vdt, np.dtype(vdt).itemsize)
        if cnt:
            if (np.diff(rowidx) < 0).any():
                raise AcgError(ErrorCode.INVALID_FORMAT,
                               "file is not row-sorted; regenerate with "
                               "mtx2bin --expand")
            if rowidx[0] < row_lo or rowidx[-1] >= row_hi:
                raise AcgError(ErrorCode.INVALID_FORMAT,
                               "row-range bisection failed (unsorted file?)")
            if colidx.min() < 0 or colidx.max() >= ncols:
                raise AcgError(ErrorCode.INDEX_OUT_OF_BOUNDS,
                               "mtx indices out of range")
    return MtxFile(object=obj, format=fmt, field=field,
                   symmetry=sym, nrows=nrows, ncols=ncols, nnz=cnt,
                   rowidx=rowidx, colidx=colidx, vals=vals,
                   comments=comments)


def write_mtx(path, mtx: MtxFile, binary: bool = False, numfmt: str = "%.17g") -> None:
    """Write a Matrix Market file (text or raw binary).

    Equivalent of ``mtxfile_fwrite_double`` (``mtxfile.h:997``); the binary
    data section matches the reference's layout (rowidx, colidx, vals as
    consecutive raw arrays, 1-based int64 indices, ``mtxfile.c:1492-1497``).
    """
    own = isinstance(path, (str, os.PathLike))
    f = open(path, "wb") if own else path
    try:
        _write_mtx_stream(f, mtx, binary, numfmt)
    finally:
        if own:
            f.close()


def _binary_vals(mtx: MtxFile) -> np.ndarray:
    """Values coerced to the on-disk binary dtype (float64 or int32),
    matching what the reader expects for the declared field."""
    vdt = np.float64 if mtx.field == "real" else np.int32
    return np.ascontiguousarray(np.asarray(mtx.vals), dtype=vdt)


def _write_mtx_stream(f, mtx: MtxFile, binary: bool, numfmt: str) -> None:
    field = "double" if (binary and mtx.field == "real") else mtx.field
    # The reference's mtx2bin keeps the header text unchanged but the data
    # binary; readers distinguish via the --binary flag, as do we.
    f.write(f"%%MatrixMarket {mtx.object} {mtx.format} {field} {mtx.symmetry}\n".encode())
    for c in mtx.comments:
        line = c if c.startswith("%") else "%" + c
        f.write((line.rstrip("\n") + "\n").encode())
    if mtx.format == "coordinate":
        f.write(f"{mtx.nrows} {mtx.ncols} {mtx.nnz}\n".encode())
        if binary:
            # tobytes + f.write (not ndarray.tofile) so stream targets work
            # and ordering with the buffered header is preserved
            f.write((np.asarray(mtx.rowidx, dtype=IDX_DTYPE) + 1).tobytes())
            f.write((np.asarray(mtx.colidx, dtype=IDX_DTYPE) + 1).tobytes())
            if mtx.vals is not None:
                f.write(_binary_vals(mtx).tobytes())
        else:
            from acg_tpu import _native
            vals64 = (None if mtx.vals is None
                      else np.ascontiguousarray(mtx.vals, np.float64))
            if _native.available() and mtx.nnz > 0:
                try:
                    f.write(_native.format_coord(mtx.rowidx, mtx.colidx,
                                                 vals64, numfmt))
                    return
                except _native.NativeParseError:
                    pass  # exotic numfmt width: python fallback below
            r = np.asarray(mtx.rowidx) + 1
            c = np.asarray(mtx.colidx) + 1
            if mtx.vals is not None:
                lines = np.char.add(np.char.add(r.astype(str), " "), c.astype(str))
                valstr = np.array([numfmt % v for v in np.asarray(mtx.vals)])
                lines = np.char.add(np.char.add(lines, " "), valstr)
                f.write(("\n".join(lines.tolist()) + "\n").encode())
            else:
                lines = np.char.add(np.char.add(r.astype(str), " "), c.astype(str))
                f.write(("\n".join(lines.tolist()) + "\n").encode())
    else:
        if mtx.object == "vector":
            f.write(f"{mtx.nrows}\n".encode())
        else:
            f.write(f"{mtx.nrows} {mtx.ncols}\n".encode())
        if binary:
            f.write(_binary_vals(mtx).tobytes())
        else:
            vals = np.asarray(mtx.vals).reshape(-1)
            from acg_tpu import _native
            if _native.available() and vals.size:
                try:
                    f.write(_native.format_array(vals, numfmt))
                    return
                except _native.NativeParseError:
                    pass
            f.write(("\n".join(numfmt % v for v in vals) + "\n").encode())


def vector_binary_header(n: int) -> bytes:
    """The exact header bytes of a binary array double vector file of
    length ``n`` -- deterministic from ``n`` alone, which is what makes
    rootless range WRITES possible: every controller computes the same
    data offset with no coordination."""
    return f"%%MatrixMarket matrix array double general\n{n} 1\n".encode()


def write_vector_window(path, n: int, row_lo: int,
                        values: np.ndarray) -> None:
    """Range-WRITE ``values`` (float64) into rows ``[row_lo, row_lo +
    len(values))`` of a binary array vector file of global length ``n``
    -- the output mirror of :func:`read_mtx_row_range` and the rootless
    restatement of the reference's rank-ordered distributed solution
    output (``mtxfile_fwrite_mpi_double``, ``mtxfile.h:1087``): each
    controller writes exactly its owned windows, I/O is O(local rows),
    and no full vector is ever gathered anywhere.

    Creates the file if needed (sparse until every window lands); call
    :func:`finalize_vector_file` from ONE process to write the header
    and pin the exact length.
    """
    values = np.ascontiguousarray(values, dtype=np.float64)
    if not (0 <= row_lo and row_lo + values.size <= n):
        raise AcgError(ErrorCode.INVALID_VALUE,
                       f"window [{row_lo}, {row_lo + values.size}) outside "
                       f"[0, {n})")
    fd = os.open(os.fspath(path), os.O_CREAT | os.O_WRONLY, 0o644)
    try:
        os.lseek(fd, len(vector_binary_header(n)) + 8 * row_lo, os.SEEK_SET)
        os.write(fd, values.tobytes())
    finally:
        os.close(fd)


def finalize_vector_file(path, n: int) -> None:
    """Write the deterministic header of a range-written vector file and
    truncate it to its exact size (one process -- the primary -- calls
    this; the reference's root writes the header the same way)."""
    hdr = vector_binary_header(n)
    fd = os.open(os.fspath(path), os.O_CREAT | os.O_WRONLY, 0o644)
    try:
        os.write(fd, hdr)
        os.ftruncate(fd, len(hdr) + 8 * n)
    finally:
        os.close(fd)


def _open_vector_binary(f, path, expect_nrows):
    """Validate an open binary array-vector stream and return
    ``(nrows, data_off, vdt)`` -- the shared header step of the window
    and gather readers (parsed ONCE per open; the gather reader issues
    many seek+reads against the same handle)."""
    if f.read(2) == b"\x1f\x8b":
        raise AcgError(ErrorCode.NOT_SUPPORTED,
                       f"{path}: gzipped vector files are not "
                       f"seekable for window reads; decompress to a "
                       f"raw binary array file first")
    f.seek(0)
    _, fmt, field, _, _, nrows, ncols, _ = _read_header_meta(f)
    if expect_nrows is not None and nrows != expect_nrows:
        raise AcgError(ErrorCode.INVALID_VALUE,
                       f"{path}: vector has {nrows} rows, "
                       f"need {expect_nrows}")
    if fmt != "array" or ncols != 1:
        raise AcgError(ErrorCode.INVALID_FORMAT,
                       f"{path}: vector window reads need a dense "
                       f"array vector file ({fmt} {ncols} cols)")
    if field == "real":
        vdt = np.dtype(np.float64)
    elif field == "integer":
        # the binary layout of integer array vectors (int32, same as
        # read_mtx binary=True) -- window reads of the perm/bounds
        # sidecars ride this
        vdt = np.dtype(np.int32)
    else:
        raise AcgError(ErrorCode.NOT_SUPPORTED,
                       f"{path}: vector windows read 'real'/'double'"
                       f"/'integer' fields (got {field!r})")
    data_off = f.tell()
    f.seek(0, os.SEEK_END)
    if f.tell() != data_off + vdt.itemsize * nrows:
        raise AcgError(ErrorCode.INVALID_FORMAT,
                       f"{path}: data section size does not match "
                       f"the binary array layout for {nrows} rows "
                       f"-- not a binary file?")
    return nrows, data_off, vdt


def _read_window_at(f, path, nrows, data_off, vdt, row_lo, row_hi):
    if not (0 <= row_lo <= row_hi):
        raise AcgError(ErrorCode.INVALID_VALUE,
                       f"bad row range [{row_lo}, {row_hi})")
    if row_hi > nrows:
        raise AcgError(ErrorCode.INVALID_VALUE,
                       f"window [{row_lo}, {row_hi}) outside "
                       f"[0, {nrows})")
    f.seek(data_off + vdt.itemsize * row_lo)
    buf = f.read(vdt.itemsize * (row_hi - row_lo))
    if len(buf) != vdt.itemsize * (row_hi - row_lo):
        raise AcgError(ErrorCode.EOF, "binary vector truncated")
    return np.frombuffer(buf, dtype=vdt).copy()


def read_vector_window(path, row_lo: int, row_hi: int,
                       expect_nrows: int | None = None) -> np.ndarray:
    """Read rows ``[row_lo, row_hi)`` of a BINARY array (dense vector)
    file -- the input mirror of :func:`write_vector_window`: one seek +
    one read of exactly the window, so per-controller right-hand-side
    ingest is O(local rows) (the b/x0 half of the reference's
    distributed file I/O, ``mtxfile.h:997-1087``).

    ``expect_nrows`` pins the file's global length: window reads only
    touch their slice, so without this check a wrong-sized vector
    (wrong problem) would be silently accepted wherever the windows
    happen to fit."""
    with open(path, "rb") as f:
        nrows, data_off, vdt = _open_vector_binary(f, path, expect_nrows)
        return _read_window_at(f, path, nrows, data_off, vdt,
                               row_lo, row_hi)


# gaps up to this many rows between requested indices are read over in
# one request rather than split into separate seeks (8 B rows: 64 rows
# = 512 B -- far below the cost of an extra syscall + disk round trip)
_GATHER_GAP_ROWS = 64


def read_vector_rows(path, rows: np.ndarray,
                     expect_nrows: int | None = None) -> np.ndarray:
    """Gather arbitrary ``rows`` (0-based, any order, duplicates OK) of
    a binary array vector file, as float64 in the requested order.

    The scattered-row mirror of :func:`read_vector_window` for
    partition-PERMUTED matrices under ``--distributed-read``: a
    controller's owned window of permuted rows maps through the perm
    sidecar to non-contiguous rows of the original-ordering b/x0 file
    (the reference reads these through its rowwise partitioned
    ``mtxfile`` gather, ``mtxfile.h:997-1087``).  I/O is coalesced:
    sorted unique indices are grouped into runs whose internal gaps are
    below ``_GATHER_GAP_ROWS``, one seek+read per run -- O(local rows)
    for the band-dominated permutations METIS produces, never worse
    than one syscall per ``_GATHER_GAP_ROWS``-spaced index."""
    rows = np.asarray(rows, dtype=np.int64).reshape(-1)
    if rows.size == 0:
        return np.zeros(0, dtype=np.float64)
    uniq, inverse = np.unique(rows, return_inverse=True)
    if uniq[0] < 0 or (expect_nrows is not None
                       and uniq[-1] >= expect_nrows):
        raise AcgError(ErrorCode.INVALID_VALUE,
                       f"gather rows outside [0, {expect_nrows})")
    # run boundaries: where the gap to the previous index exceeds the
    # coalescing threshold
    cuts = np.flatnonzero(np.diff(uniq) > _GATHER_GAP_ROWS) + 1
    starts = np.concatenate([[0], cuts])
    ends = np.concatenate([cuts, [uniq.size]])
    vals = np.empty(uniq.size, dtype=np.float64)
    # ONE open + header parse for the whole gather: a scattered perm can
    # produce O(local rows / gap) runs, and per-run re-validation
    # (open + parse + seek-to-end) would multiply the syscall count
    with open(path, "rb") as f:
        nrows, data_off, vdt = _open_vector_binary(f, path, expect_nrows)
        # validate against the PARSED row count too (expect_nrows is
        # optional): an out-of-range gather row is named directly here
        # instead of surfacing as a window-range error mid-read
        if uniq[-1] >= nrows:
            raise AcgError(ErrorCode.INVALID_VALUE,
                           f"{path}: gather row {int(uniq[-1])} outside "
                           f"the file's [0, {nrows}) rows")
        for s, e in zip(starts, ends):
            lo, hi = int(uniq[s]), int(uniq[e - 1]) + 1
            chunk = _read_window_at(f, path, nrows, data_off, vdt, lo, hi)
            vals[s:e] = chunk[uniq[s:e] - lo]
    return vals[inverse]


def vector_mtx(x: np.ndarray, field: str = "real") -> MtxFile:
    """Wrap a dense vector as a Matrix Market array file object."""
    x = np.asarray(x)
    return MtxFile(object="matrix", format="array", field=field,
                   symmetry="general", nrows=x.size, ncols=1,
                   nnz=x.size, vals=x)


def multi_vector_mtx(X: np.ndarray, field: str = "real") -> MtxFile:
    """Wrap an (n, B) COLUMN BLOCK as a dense Matrix Market array file
    (the batched tier's multi-RHS b / solution container).  Values are
    stored column-major, the Matrix Market array convention."""
    X = np.asarray(X)
    if X.ndim == 1:
        X = X[:, None]
    return MtxFile(object="matrix", format="array", field=field,
                   symmetry="general", nrows=X.shape[0],
                   ncols=X.shape[1], nnz=X.size,
                   vals=np.asarray(X, order="F").reshape(-1, order="F"))


def vector_columns(mtx: MtxFile, n: int, nrhs: int) -> np.ndarray:
    """Extract an (n, nrhs) column block from a dense array MtxFile --
    the multi-column b/x0 ingest of ``--nrhs``.  Accepts a file whose
    header declares exactly ``n x nrhs`` (column-major data, the MTX
    array convention); anything else refuses self-describingly rather
    than silently reshaping someone else's vector."""
    if mtx.format != "array":
        raise AcgError(
            ErrorCode.INVALID_FORMAT,
            f"--nrhs {nrhs} needs a DENSE array file of {n} x {nrhs} "
            f"values (one column per right-hand side); this file is "
            f"{mtx.format} format")
    vals = np.asarray(mtx.vals, dtype=np.float64).reshape(-1)
    if mtx.ncols != nrhs or mtx.nrows != n or vals.size != n * nrhs:
        raise AcgError(
            ErrorCode.INVALID_VALUE,
            f"--nrhs {nrhs} needs a {n} x {nrhs} array file; this "
            f"file declares {mtx.nrows} x {mtx.ncols} "
            f"({vals.size} values)")
    return vals.reshape((n, nrhs), order="F")
