"""Model-problem generators: 2D/3D Poisson finite-difference matrices.

Rebuilds (and extends to 3D) the reference's ``matrices_generator/poisson.py``
(5-point 2D Poisson on an n x n grid).  Returns COO triplets of the FULL
symmetric matrix; callers needing one-triangle storage filter ``r <= c``.
The benchmark protocol (BASELINE.md) uses 2D n=2048 and 3D up to 512^3.
"""

from __future__ import annotations

import numpy as np

from acg_tpu.io.mtxfile import IDX_DTYPE, MtxFile


def poisson2d_coo(n: int, dtype=np.float64):
    """5-point 2D Poisson stencil on an n x n grid -> full COO (N = n*n)."""
    idx = np.arange(n * n, dtype=IDX_DTYPE)
    i, j = idx // n, idx % n
    rows = [idx]
    cols = [idx]
    vals = [np.full(n * n, 4.0, dtype=dtype)]
    for di, dj in ((-1, 0), (1, 0), (0, -1), (0, 1)):
        ii, jj = i + di, j + dj
        ok = (ii >= 0) & (ii < n) & (jj >= 0) & (jj < n)
        rows.append(idx[ok])
        cols.append((ii * n + jj)[ok])
        vals.append(np.full(ok.sum(), -1.0, dtype=dtype))
    return np.concatenate(rows), np.concatenate(cols), np.concatenate(vals), n * n


def aniso_poisson2d_coo(n: int, eps: float, dtype=np.float64):
    """Anisotropic/STRETCHED 2D Poisson on an n x n tensor grid -> full
    COO (N = n*n): the Laplacian assembled symmetrically (FV/FEM edge
    weights) on a grid whose y-spacings shrink geometrically by the
    stretch factor ``eps = h_min/h_max <= 1``.

    The ill-conditioned SPD family of the preconditioning tier
    (acg_tpu.precond): x-edge weights span ``[eps, 1]`` and y-edge
    weights ``[1, 1/eps]``, so the DIAGONAL varies by ~1/eps across the
    grid -- unlike the constant-diagonal uniform stencil, where Jacobi
    is a no-op scaling -- and the condition number grows ~1/eps beyond
    the uniform grid's.  Measured at n=256, eps=0.01 (f64, rtol 1e-6):
    CG 2956 iterations unpreconditioned, 992 with ``--precond jacobi``
    (3.0x), 718 with ``--precond cheby:4`` (4.1x).

    SPD by construction: a positively-weighted graph Laplacian plus
    Dirichlet boundary terms (symmetric, weakly diagonally dominant,
    strictly at the boundary rows, irreducible).
    """
    if not 0.0 < eps <= 1.0:
        raise ValueError(f"aniso stretch factor must be in (0, 1], "
                         f"got {eps}")
    j = np.arange(n)
    # x-edge weight in grid row j (the h_y(j)/h_x FEM factor) and
    # y-edge weight at horizontal edge e (1/h_y; e = 0 and n are the
    # Dirichlet boundary edges)
    wx = (eps ** ((j + 0.5) / n)).astype(dtype)
    e = np.arange(n + 1)
    wy = (eps ** (-(e / n))).astype(dtype)

    def idx(jj, ii):
        return (jj * n + ii).astype(IDX_DTYPE)

    J, I = np.meshgrid(j, j, indexing="ij")
    rows = [idx(J, I).ravel()]
    cols = [idx(J, I).ravel()]
    vals = [(2 * wx[J] + wy[J] + wy[J + 1]).ravel()]
    for di in (-1, 1):
        ok = (I + di >= 0) & (I + di < n)
        rows.append(idx(J, I)[ok])
        cols.append(idx(J, I + di)[ok])
        vals.append(-wx[J][ok])
    ok = J + 1 < n     # edge between grid rows j and j+1 weighs wy[j+1]
    rows.append(idx(J, I)[ok])
    cols.append(idx(J + 1, I)[ok])
    vals.append(-wy[J + 1][ok])
    rows.append(idx(J + 1, I)[ok])
    cols.append(idx(J, I)[ok])
    vals.append(-wy[J + 1][ok])
    return (np.concatenate(rows), np.concatenate(cols),
            np.concatenate(vals), n * n)


def poisson3d_coo(n: int, dtype=np.float64):
    """7-point 3D Poisson stencil on an n^3 grid -> full COO (N = n^3)."""
    N = n * n * n
    idx = np.arange(N, dtype=IDX_DTYPE)
    i, j, k = idx // (n * n), (idx // n) % n, idx % n
    rows = [idx]
    cols = [idx]
    vals = [np.full(N, 6.0, dtype=dtype)]
    for di, dj, dk in ((-1, 0, 0), (1, 0, 0), (0, -1, 0), (0, 1, 0), (0, 0, -1), (0, 0, 1)):
        ii, jj, kk = i + di, j + dj, k + dk
        ok = (ii >= 0) & (ii < n) & (jj >= 0) & (jj < n) & (kk >= 0) & (kk < n)
        rows.append(idx[ok])
        cols.append(((ii * n + jj) * n + kk)[ok])
        vals.append(np.full(ok.sum(), -1.0, dtype=dtype))
    return np.concatenate(rows), np.concatenate(cols), np.concatenate(vals), N


def poisson_dia(n: int, dim: int = 2, dtype=np.float64):
    """Poisson stencil assembled DIRECTLY as DIA planes -- no COO/CSR
    intermediate, no sort: O(ndiags * N) time and memory.

    This is how a stencil matrix should reach a TPU: the reference goes
    scipy COO -> .mtx file -> parse -> CSR (``matrices_generator/
    poisson.py``), which at N=512^3 (134M rows, ~0.9G nnz) costs tens of
    GB and minutes of preprocessing; the DIA planes ARE the device
    format, built here in one vectorised pass per diagonal.

    Returns ``(planes, offsets, N)`` with the package DIA convention
    ``planes[d][r] = A[r, r + offsets[d]]`` (``ops.spmv.DiaMatrix``).
    """
    N = n ** dim
    diag_val = float(2 * dim)
    offsets, planes = [], []
    # per-axis neighbour pairs: axis a (0 = fastest-varying) has stride
    # n^a and coordinate (r // n^a) % n; the entry A[r, r +- n^a] exists
    # unless the coordinate sits on that boundary.  Viewed as
    # (N/period, n, stride), the boundary rows are one slice of the
    # middle axis -- so each plane is a flat fill plus one strided zero
    # write of N/n entries, no index arithmetic over N at all
    for a in range(dim):
        stride = n ** a
        lo = np.full(N, -1.0, dtype=dtype)
        lo.reshape(-1, n, stride)[:, 0, :] = 0.0
        hi = np.full(N, -1.0, dtype=dtype)
        hi.reshape(-1, n, stride)[:, -1, :] = 0.0
        offsets += [-stride, stride]
        planes += [lo, hi]
    offsets.append(0)
    planes.append(np.full(N, diag_val, dtype=dtype))
    order = np.argsort(offsets)
    return ([planes[i] for i in order],
            tuple(int(offsets[i]) for i in order), N)


def poisson_dia_device(n: int, dim: int = 2, dtype=None):
    """Poisson DIA planes assembled ON DEVICE as one jitted program.

    Same output as :func:`poisson_dia` but with zero host->device
    transfer: the planes are fills plus boundary masks, which XLA
    computes from iotas directly in HBM.  At 512^3 this replaces a
    3.8 GB upload (minutes over a tunneled chip, seconds over PCIe)
    with a sub-second device computation -- the stencil analog of the
    reference generating its matrix on the host and shipping it to
    every GPU (``matrices_generator/poisson.py`` + scatter).

    Returns ``(planes, offsets, N)`` with planes as jax arrays.
    """
    import jax
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.float32
    N = n ** dim

    @jax.jit
    def build():
        planes = []
        for a in range(dim):
            stride = n ** a
            coord = (jax.lax.iota(jnp.int32, N) // stride) % n
            planes.append(jnp.where(coord > 0, -1.0, 0.0).astype(dtype))
            planes.append(jnp.where(coord < n - 1, -1.0, 0.0).astype(dtype))
        planes.append(jnp.full((N,), float(2 * dim), dtype=dtype))
        return planes

    # build() order: [lo_a0, hi_a0, lo_a1, hi_a1, ..., diag]
    offsets = [s for a in range(dim) for s in (-(n ** a), n ** a)] + [0]
    order = np.argsort(offsets)
    planes = build()
    return ([planes[i] for i in order],
            tuple(int(offsets[i]) for i in order), N)


def batched_rhs(n: int, nrhs: int, seed: int = 42,
                dtype=np.float64) -> np.ndarray:
    """Default multi-RHS block for ``--nrhs B``: B random unit-norm
    columns (seeded).  Random, NOT replicated ones: parallel columns
    would collapse the block Krylov space to rank 1, making every
    batched/block measurement degenerate -- a serving fleet's requests
    differ, and so must the default benchmark block."""
    rng = np.random.default_rng(seed)
    B = rng.standard_normal((n, int(nrhs))).astype(dtype)
    B /= np.linalg.norm(B, axis=0, keepdims=True)
    return B


def irregular_spd_coo(n: int, avg_degree: float = 16.0, seed: int = 0,
                      dtype=np.float64):
    """Random irregular SPD matrix -> full COO.

    Stands in for the irregular SuiteSparse SPD workloads of the
    benchmark protocol (BASELINE.json configs 4-5: Flan_1565, Serena,
    Queen_4147 -- not redistributable here): a configuration-model graph
    whose degrees follow a truncated power law (so row lengths vary by
    orders of magnitude, defeating banded/DIA layouts and exercising the
    ELL/gather SpMV paths), with negative off-diagonal weights and a
    strictly diagonally dominant diagonal -> symmetric positive
    definite.

    Note: every row sums to exactly 1 (diag = 1 + sum|offdiag|), so
    ``b = ones`` is an eigenvector and CG converges on it in one
    iteration -- use a manufactured solution (random xsol, b = A xsol)
    for convergence behaviour; fixed-iteration timing is unaffected.
    """
    rng = np.random.default_rng(seed)
    # power-law-ish stub counts: most rows short, a heavy tail of hubs
    # pareto(2.2)+1 has mean ~1.83; scale so mean stubs/row ~ avg_degree
    # (each stub becomes one off-diagonal entry in its own row)
    deg = np.minimum((rng.pareto(2.2, n) + 1.0) * (avg_degree * 0.546),
                     n // 4).astype(np.int64)
    stubs = np.repeat(np.arange(n, dtype=IDX_DTYPE), deg)
    rng.shuffle(stubs)
    if stubs.size % 2:
        stubs = stubs[:-1]
    u, v = stubs[0::2], stubs[1::2]
    keep = u != v
    u, v = u[keep], v[keep]
    lo, hi = np.minimum(u, v), np.maximum(u, v)
    edges = np.unique(lo.astype(np.int64) * n + hi)
    lo, hi = (edges // n).astype(IDX_DTYPE), (edges % n).astype(IDX_DTYPE)
    w = -(0.1 + rng.random(lo.size)).astype(dtype)
    # diagonal = 1 + sum of |offdiag| per row -> strict dominance
    diag = np.ones(n, dtype=dtype)
    np.add.at(diag, lo, -w)
    np.add.at(diag, hi, -w)
    idx = np.arange(n, dtype=IDX_DTYPE)
    rows = np.concatenate([idx, lo, hi])
    cols = np.concatenate([idx, hi, lo])
    vals = np.concatenate([diag, w, w])
    return rows, cols, vals, n


def irregular_mtx(n: int, avg_degree: float = 16.0, seed: int = 0) -> MtxFile:
    """Irregular SPD matrix as a symmetric (lower-triangle) MtxFile."""
    r, c, v, N = irregular_spd_coo(n, avg_degree, seed)
    keep = r >= c
    order = np.lexsort((c[keep], r[keep]))
    return MtxFile(object="matrix", format="coordinate", field="real",
                   symmetry="symmetric", nrows=N, ncols=N, nnz=int(keep.sum()),
                   rowidx=r[keep][order], colidx=c[keep][order],
                   vals=v[keep][order])


def poisson_mtx(n: int, dim: int = 2) -> MtxFile:
    """Poisson matrix as a symmetric (lower-triangle) MtxFile."""
    if dim == 2:
        r, c, v, N = poisson2d_coo(n)
    elif dim == 3:
        r, c, v, N = poisson3d_coo(n)
    else:
        raise ValueError(f"dim must be 2 or 3, got {dim}")
    keep = r >= c  # store lower triangle once, symmetry declared in header
    order = np.lexsort((c[keep], r[keep]))
    return MtxFile(object="matrix", format="coordinate", field="real",
                   symmetry="symmetric", nrows=N, ncols=N, nnz=int(keep.sum()),
                   rowidx=r[keep][order], colidx=c[keep][order],
                   vals=v[keep][order],
                   comments=[f"% acg-tpu poisson{dim}d n={n}"])
