"""Model-problem generators: 2D/3D Poisson finite-difference matrices.

Rebuilds (and extends to 3D) the reference's ``matrices_generator/poisson.py``
(5-point 2D Poisson on an n x n grid).  Returns COO triplets of the FULL
symmetric matrix; callers needing one-triangle storage filter ``r <= c``.
The benchmark protocol (BASELINE.md) uses 2D n=2048 and 3D up to 512^3.
"""

from __future__ import annotations

import numpy as np

from acg_tpu.io.mtxfile import IDX_DTYPE, MtxFile


def poisson2d_coo(n: int, dtype=np.float64):
    """5-point 2D Poisson stencil on an n x n grid -> full COO (N = n*n)."""
    idx = np.arange(n * n, dtype=IDX_DTYPE)
    i, j = idx // n, idx % n
    rows = [idx]
    cols = [idx]
    vals = [np.full(n * n, 4.0, dtype=dtype)]
    for di, dj in ((-1, 0), (1, 0), (0, -1), (0, 1)):
        ii, jj = i + di, j + dj
        ok = (ii >= 0) & (ii < n) & (jj >= 0) & (jj < n)
        rows.append(idx[ok])
        cols.append((ii * n + jj)[ok])
        vals.append(np.full(ok.sum(), -1.0, dtype=dtype))
    return np.concatenate(rows), np.concatenate(cols), np.concatenate(vals), n * n


def poisson3d_coo(n: int, dtype=np.float64):
    """7-point 3D Poisson stencil on an n^3 grid -> full COO (N = n^3)."""
    N = n * n * n
    idx = np.arange(N, dtype=IDX_DTYPE)
    i, j, k = idx // (n * n), (idx // n) % n, idx % n
    rows = [idx]
    cols = [idx]
    vals = [np.full(N, 6.0, dtype=dtype)]
    for di, dj, dk in ((-1, 0, 0), (1, 0, 0), (0, -1, 0), (0, 1, 0), (0, 0, -1), (0, 0, 1)):
        ii, jj, kk = i + di, j + dj, k + dk
        ok = (ii >= 0) & (ii < n) & (jj >= 0) & (jj < n) & (kk >= 0) & (kk < n)
        rows.append(idx[ok])
        cols.append(((ii * n + jj) * n + kk)[ok])
        vals.append(np.full(ok.sum(), -1.0, dtype=dtype))
    return np.concatenate(rows), np.concatenate(cols), np.concatenate(vals), N


def poisson_mtx(n: int, dim: int = 2) -> MtxFile:
    """Poisson matrix as a symmetric (lower-triangle) MtxFile."""
    if dim == 2:
        r, c, v, N = poisson2d_coo(n)
    elif dim == 3:
        r, c, v, N = poisson3d_coo(n)
    else:
        raise ValueError(f"dim must be 2 or 3, got {dim}")
    keep = r >= c  # store lower triangle once, symmetry declared in header
    order = np.lexsort((c[keep], r[keep]))
    return MtxFile(object="matrix", format="coordinate", field="real",
                   symmetry="symmetric", nrows=N, ncols=N, nnz=int(keep.sum()),
                   rowidx=r[keep][order], colidx=c[keep][order],
                   vals=v[keep][order],
                   comments=[f"% acg-tpu poisson{dim}d n={n}"])
