"""Model-problem generators: 2D/3D Poisson finite-difference matrices.

Rebuilds (and extends to 3D) the reference's ``matrices_generator/poisson.py``
(5-point 2D Poisson on an n x n grid).  Returns COO triplets of the FULL
symmetric matrix; callers needing one-triangle storage filter ``r <= c``.
The benchmark protocol (BASELINE.md) uses 2D n=2048 and 3D up to 512^3.
"""

from __future__ import annotations

import numpy as np

from acg_tpu.io.mtxfile import IDX_DTYPE, MtxFile


def poisson2d_coo(n: int, dtype=np.float64):
    """5-point 2D Poisson stencil on an n x n grid -> full COO (N = n*n)."""
    idx = np.arange(n * n, dtype=IDX_DTYPE)
    i, j = idx // n, idx % n
    rows = [idx]
    cols = [idx]
    vals = [np.full(n * n, 4.0, dtype=dtype)]
    for di, dj in ((-1, 0), (1, 0), (0, -1), (0, 1)):
        ii, jj = i + di, j + dj
        ok = (ii >= 0) & (ii < n) & (jj >= 0) & (jj < n)
        rows.append(idx[ok])
        cols.append((ii * n + jj)[ok])
        vals.append(np.full(ok.sum(), -1.0, dtype=dtype))
    return np.concatenate(rows), np.concatenate(cols), np.concatenate(vals), n * n


def poisson3d_coo(n: int, dtype=np.float64):
    """7-point 3D Poisson stencil on an n^3 grid -> full COO (N = n^3)."""
    N = n * n * n
    idx = np.arange(N, dtype=IDX_DTYPE)
    i, j, k = idx // (n * n), (idx // n) % n, idx % n
    rows = [idx]
    cols = [idx]
    vals = [np.full(N, 6.0, dtype=dtype)]
    for di, dj, dk in ((-1, 0, 0), (1, 0, 0), (0, -1, 0), (0, 1, 0), (0, 0, -1), (0, 0, 1)):
        ii, jj, kk = i + di, j + dj, k + dk
        ok = (ii >= 0) & (ii < n) & (jj >= 0) & (jj < n) & (kk >= 0) & (kk < n)
        rows.append(idx[ok])
        cols.append(((ii * n + jj) * n + kk)[ok])
        vals.append(np.full(ok.sum(), -1.0, dtype=dtype))
    return np.concatenate(rows), np.concatenate(cols), np.concatenate(vals), N


def irregular_spd_coo(n: int, avg_degree: float = 16.0, seed: int = 0,
                      dtype=np.float64):
    """Random irregular SPD matrix -> full COO.

    Stands in for the irregular SuiteSparse SPD workloads of the
    benchmark protocol (BASELINE.json configs 4-5: Flan_1565, Serena,
    Queen_4147 -- not redistributable here): a configuration-model graph
    whose degrees follow a truncated power law (so row lengths vary by
    orders of magnitude, defeating banded/DIA layouts and exercising the
    ELL/gather SpMV paths), with negative off-diagonal weights and a
    strictly diagonally dominant diagonal -> symmetric positive
    definite.
    """
    rng = np.random.default_rng(seed)
    # power-law-ish stub counts: most rows short, a heavy tail of hubs
    # pareto(2.2)+1 has mean ~1.83; scale so mean stubs/row ~ avg_degree
    # (each stub becomes one off-diagonal entry in its own row)
    deg = np.minimum((rng.pareto(2.2, n) + 1.0) * (avg_degree * 0.546),
                     n // 4).astype(np.int64)
    stubs = np.repeat(np.arange(n, dtype=IDX_DTYPE), deg)
    rng.shuffle(stubs)
    if stubs.size % 2:
        stubs = stubs[:-1]
    u, v = stubs[0::2], stubs[1::2]
    keep = u != v
    u, v = u[keep], v[keep]
    lo, hi = np.minimum(u, v), np.maximum(u, v)
    edges = np.unique(lo.astype(np.int64) * n + hi)
    lo, hi = (edges // n).astype(IDX_DTYPE), (edges % n).astype(IDX_DTYPE)
    w = -(0.1 + rng.random(lo.size)).astype(dtype)
    # diagonal = 1 + sum of |offdiag| per row -> strict dominance
    diag = np.ones(n, dtype=dtype)
    np.add.at(diag, lo, -w)
    np.add.at(diag, hi, -w)
    idx = np.arange(n, dtype=IDX_DTYPE)
    rows = np.concatenate([idx, lo, hi])
    cols = np.concatenate([idx, hi, lo])
    vals = np.concatenate([diag, w, w])
    return rows, cols, vals, n


def irregular_mtx(n: int, avg_degree: float = 16.0, seed: int = 0) -> MtxFile:
    """Irregular SPD matrix as a symmetric (lower-triangle) MtxFile."""
    r, c, v, N = irregular_spd_coo(n, avg_degree, seed)
    keep = r >= c
    order = np.lexsort((c[keep], r[keep]))
    return MtxFile(object="matrix", format="coordinate", field="real",
                   symmetry="symmetric", nrows=N, ncols=N, nnz=int(keep.sum()),
                   rowidx=r[keep][order], colidx=c[keep][order],
                   vals=v[keep][order])


def poisson_mtx(n: int, dim: int = 2) -> MtxFile:
    """Poisson matrix as a symmetric (lower-triangle) MtxFile."""
    if dim == 2:
        r, c, v, N = poisson2d_coo(n)
    elif dim == 3:
        r, c, v, N = poisson3d_coo(n)
    else:
        raise ValueError(f"dim must be 2 or 3, got {dim}")
    keep = r >= c  # store lower triangle once, symmetry declared in header
    order = np.lexsort((c[keep], r[keep]))
    return MtxFile(object="matrix", format="coordinate", field="real",
                   symmetry="symmetric", nrows=N, ncols=N, nnz=int(keep.sum()),
                   rowidx=r[keep][order], colidx=c[keep][order],
                   vals=v[keep][order],
                   comments=[f"% acg-tpu poisson{dim}d n={n}"])
