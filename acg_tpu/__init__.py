"""acg-tpu: TPU-native distributed conjugate gradient solvers.

A brand-new TPU-first implementation of the capabilities of aCG
(GPU-accelerated CG solvers for SPD sparse systems, SC'25): classic CG and
Ghysels-Vanroose pipelined CG over partitioned symmetric CSR matrices, with
halo exchange and dot-product allreduce expressed as XLA collectives /
Pallas remote DMA over a TPU device mesh.

Layering (mirrors the reference's layer map, SURVEY.md section 1, rebuilt
TPU-first rather than ported):

  L0  acg_tpu.errors, acg_tpu.io.mtxfile, acg_tpu.utils.*   (foundation)
  L1  acg_tpu.graph, acg_tpu.partition                      (partitioning)
  L2  acg_tpu.parallel.comm                                 (collectives)
  L3  acg_tpu.parallel.halo                                 (halo exchange)
  L4  acg_tpu.matrix, acg_tpu.vector                        (sparse linalg)
  L5  acg_tpu.solvers.*                                     (CG solvers)
  L6  acg_tpu.tools.*                                       (offline tools)
  L7  acg_tpu.cli                                           (driver)

This module intentionally does NOT import jax at top level so that pure
host-side preprocessing (I/O, partitioning) stays importable and fast in
contexts without an accelerator runtime.
"""

__version__ = "0.1.0"

from acg_tpu.errors import AcgError, ErrorCode  # noqa: F401
