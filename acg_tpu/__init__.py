"""acg-tpu: TPU-native distributed conjugate gradient solvers.

A brand-new TPU-first implementation of the capabilities of aCG
(GPU-accelerated CG solvers for SPD sparse systems, SC'25): classic CG and
Ghysels-Vanroose pipelined CG over partitioned symmetric CSR matrices, with
halo exchange and dot-product allreduce expressed as XLA collectives /
Pallas remote DMA over a TPU device mesh.

Layering (mirrors the reference's layer map, SURVEY.md section 1, rebuilt
TPU-first rather than ported):

  L0  acg_tpu.errors, acg_tpu.io.mtxfile, acg_tpu.fmtspec,
      acg_tpu._native                                       (foundation)
  L1  acg_tpu.graph, acg_tpu.partition                      (partitioning)
  L2  acg_tpu.parallel.mesh, acg_tpu.parallel.multihost     (communicator)
  L3  acg_tpu.parallel.halo, acg_tpu.parallel.halo_dma      (halo exchange)
  L4  acg_tpu.matrix, acg_tpu.vector, acg_tpu.ops.*         (sparse linalg)
  L5  acg_tpu.solvers.*, acg_tpu.parallel.dist              (CG solvers)
  L6  acg_tpu.tools.*                                       (offline tools)
  L7  acg_tpu.cli                                           (driver)

This module does NOT import jax at top level, so pure host-side
preprocessing (I/O, partitioning, the host oracles) stays importable and
fast in contexts without an accelerator runtime; the jax-backed solvers
(`JaxCGSolver`, `DistCGSolver`, `DistributedProblem`, `solve_mesh`) are
exposed lazily and import jax on first access.
"""

__version__ = "0.1.0"

from acg_tpu.errors import AcgError, ErrorCode  # noqa: F401
from acg_tpu.solvers.host_cg import (HostCGSolver,  # noqa: F401
                                     HostDistCGSolver, NativeHostCGSolver)
from acg_tpu.solvers.stats import SolverStats, StoppingCriteria  # noqa: F401

_LAZY = {
    "solve_mesh": "acg_tpu.parallel.mesh",
    "DistributedProblem": "acg_tpu.parallel.dist",
    "DistCGSolver": "acg_tpu.parallel.dist",
    "JaxCGSolver": "acg_tpu.solvers.jax_cg",
}


def __getattr__(name):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(target), name)


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
