"""ctypes bindings for the native host core (``native/libacg_core.so``).

The reference's host layers are native C (SURVEY.md section 2); ours are
C++ behind this module.  Every binding has a pure-numpy fallback in the
package (``io.mtxfile``, ``matrix``, ``graph``), selected automatically
when the shared library is absent or ``ACG_TPU_DISABLE_NATIVE=1``.  On
first import the library is built with ``make -C native`` if the checkout
contains the sources but no binary.

All wrappers take/return numpy arrays; int64 indices throughout (reference
``acgidx_t`` at IDXSIZE=64).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_I64 = ctypes.POINTER(ctypes.c_int64)
_I32 = ctypes.POINTER(ctypes.c_int32)
_F64 = ctypes.POINTER(ctypes.c_double)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libacg_core.so")

_lib = None


_FAIL_STAMP = os.path.join(_NATIVE_DIR, ".build_failed")


def _try_build() -> bool:
    """Build once per checkout; a failure stamp prevents every subsequent
    process from re-running make, the whole make invocation runs under an
    exclusive file lock (concurrent first imports would otherwise race on
    the shared src/*.o targets and could link a corrupted library), and
    the .so is linked to a temp name and atomically renamed so concurrent
    importers never dlopen a half-linked file."""
    if not os.path.exists(os.path.join(_NATIVE_DIR, "Makefile")):
        return False
    if os.path.exists(_FAIL_STAMP):
        return False
    tmp = _LIB_PATH + f".build.{os.getpid()}"
    lock = None
    try:
        # best effort: a failed lock (non-POSIX, NFS without lockd, ...)
        # must fall back to an unlocked build, not poison the fail stamp
        import fcntl
        lock = open(_LIB_PATH + ".lock", "w")
        fcntl.flock(lock, fcntl.LOCK_EX)
    except (ImportError, OSError):
        pass
    try:
        # another process may have finished the build while we waited
        if os.path.exists(_LIB_PATH):
            return True
        if os.path.exists(_FAIL_STAMP):
            return False
        subprocess.run(["make", "-C", _NATIVE_DIR,
                        f"LIB={os.path.basename(tmp)}"],
                       check=True, capture_output=True, timeout=180)
        os.replace(tmp, _LIB_PATH)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            with open(_FAIL_STAMP, "w") as f:
                f.write("native build failed; delete this file to retry\n")
        except OSError:
            pass
        return False
    finally:
        if lock is not None:
            try:
                lock.close()
            except OSError:
                pass
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


_ABI_VERSION = 3  # must match acg_core_abi_version() (native/src/sort.cpp)


def _open_and_bind(path=None):
    """CDLL + version check + symbol binding; None on any mismatch (a
    missing symbol or wrong version means a stale library)."""
    try:
        lib = ctypes.CDLL(path or _LIB_PATH)
    except OSError:
        return None
    c = ctypes.c_int64
    try:
        lib.acg_core_abi_version.restype = ctypes.c_int32
        if lib.acg_core_abi_version() != _ABI_VERSION:
            return None
        _bind(lib, c)
    except AttributeError:
        return None
    return lib


def _load():
    global _lib
    if os.environ.get("ACG_TPU_DISABLE_NATIVE"):
        return None
    if not os.path.exists(_LIB_PATH) and not _try_build():
        return None
    lib = _open_and_bind()
    if (lib is None and os.path.exists(_LIB_PATH)
            and os.path.exists(os.path.join(_NATIVE_DIR, "Makefile"))):
        # stale library from an older checkout: rebuild once
        try:
            os.remove(_LIB_PATH)
        except OSError:
            return None
        if _try_build():
            lib = _open_and_bind()
            if lib is None:
                # dlopen caches the stale mapping by pathname; load the
                # fresh build through a unique temp path (safe to unlink
                # once dlopened)
                import shutil
                import tempfile

                fd, tmp = tempfile.mkstemp(suffix=".so")
                os.close(fd)
                try:
                    shutil.copy2(_LIB_PATH, tmp)
                    lib = _open_and_bind(tmp)
                finally:
                    try:
                        os.remove(tmp)
                    except OSError:
                        pass
    _lib = lib
    return lib


def _bind(lib, c):
    lib.acg_radixsort_i64.argtypes = [c, _I64, _I64]
    lib.acg_radixargsort_i64.argtypes = [c, _I64, _I64]
    lib.acg_prefixsum_exclusive_i64.argtypes = [c, _I64]
    lib.acg_mtx_parse_coord.restype = c
    lib.acg_mtx_parse_coord.argtypes = [
        ctypes.c_char_p, c, c, c, c, ctypes.c_int32, _I64, _I64, _F64]
    lib.acg_mtx_parse_array.restype = c
    lib.acg_mtx_parse_array.argtypes = [ctypes.c_char_p, c, c, _F64]
    lib.acg_mtx_format_coord.restype = c
    lib.acg_mtx_format_coord.argtypes = [
        c, _I64, _I64, _F64, ctypes.c_char_p, ctypes.c_char_p, c]
    lib.acg_mtx_format_array.restype = c
    lib.acg_mtx_format_array.argtypes = [
        c, _F64, ctypes.c_char_p, ctypes.c_char_p, c]
    lib.acg_sym_csr_count.restype = c
    lib.acg_sym_csr_count.argtypes = [c, c, _I64, _I64, _I64, _I64, _I32]
    lib.acg_sym_csr_fill.restype = c
    lib.acg_sym_csr_fill.argtypes = [c, c, c, _I64, _I64, _F64,
                                     ctypes.c_int32, _I64, _I64, _F64]
    lib.acg_sym_csr_expand.restype = c
    lib.acg_sym_csr_expand.argtypes = [c, _I64, _I64, _F64,
                                       ctypes.c_double, _I64, _I64, _F64, c]
    lib.acg_graph_partition_run.restype = ctypes.c_void_p
    lib.acg_graph_partition_run.argtypes = [c, _I64, _I64, _I32,
                                            ctypes.c_int32]
    lib.acg_pr_counts.argtypes = [ctypes.c_void_p, _I64, _I64, _I64, _I64]
    lib.acg_pr_fill.argtypes = [ctypes.c_void_p, _I64, _I32, _I32, _I64,
                                _I64]
    lib.acg_pr_free.argtypes = [ctypes.c_void_p]
    lib.acg_cg_solve.restype = ctypes.c_int32
    lib.acg_cg_solve.argtypes = [
        c, _I64, _I64, _F64, _F64, _F64, ctypes.c_int32,
        ctypes.c_double, ctypes.c_double, ctypes.c_double, ctypes.c_double,
        _I32, _F64, _F64, _F64, _F64]


_lib = _load()


def available() -> bool:
    return _lib is not None


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctype) if a.size else None


def _i64(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.int64)


# ---- sort / scan ---------------------------------------------------------

def radixsort(keys: np.ndarray, return_perm: bool = True):
    """Sort int64 keys ascending (stable); optionally return the argsort."""
    keys = _i64(keys).copy()
    n = keys.size
    perm = np.empty(n, dtype=np.int64) if return_perm else None
    _lib.acg_radixsort_i64(n, _ptr(keys, _I64),
                           _ptr(perm, _I64) if return_perm else None)
    return (keys, perm) if return_perm else keys


def argsort(keys: np.ndarray) -> np.ndarray:
    keys = _i64(keys)
    perm = np.empty(keys.size, dtype=np.int64)
    _lib.acg_radixargsort_i64(keys.size, _ptr(keys, _I64), _ptr(perm, _I64))
    return perm


def prefixsum_exclusive(a: np.ndarray) -> np.ndarray:
    """[a0, a1, ...] -> [0, a0, a0+a1, ..., total] (n+1 entries)."""
    a = _i64(a)
    out = np.empty(a.size + 1, dtype=np.int64)
    out[: a.size] = a
    out[a.size] = 0
    _lib.acg_prefixsum_exclusive_i64(a.size, _ptr(out, _I64))
    return out


# ---- Matrix Market data sections ----------------------------------------

class NativeParseError(Exception):
    def __init__(self, code: int):
        super().__init__(f"native parse error {code}")
        self.code = int(code)


def parse_coord(buf: bytes, nnz: int, nrows: int, ncols: int,
                with_vals: bool):
    """Parse coordinate data lines; returns (rowidx, colidx, vals|None),
    0-based and bounds-checked."""
    rowidx = np.empty(nnz, dtype=np.int64)
    colidx = np.empty(nnz, dtype=np.int64)
    vals = np.empty(nnz, dtype=np.float64) if with_vals else None
    rc = _lib.acg_mtx_parse_coord(
        buf, len(buf), nnz, nrows, ncols, 1 if with_vals else 0,
        _ptr(rowidx, _I64), _ptr(colidx, _I64),
        _ptr(vals, _F64) if with_vals else None)
    if rc < 0:
        raise NativeParseError(rc)
    return rowidx, colidx, vals


def parse_array(buf: bytes, n: int) -> np.ndarray:
    vals = np.empty(n, dtype=np.float64)
    rc = _lib.acg_mtx_parse_array(buf, len(buf), n, _ptr(vals, _F64))
    if rc < 0:
        raise NativeParseError(rc)
    return vals


import re

_FLOAT_FMT = re.compile(r"^[^%]*%[-+ #0-9.]*[eEfFgG][^%]*$")


def _fmt_width(fmt: str) -> int:
    """Upper-bound the printed width of one value under ``fmt`` by probing
    extreme doubles (overflow is caught by the C side and surfaces as a
    NativeParseError, so a too-small probe only costs a fallback).  Only
    float conversions are supported: the C side passes a double vararg, so
    %d-style formats must take the Python fallback."""
    if not _FLOAT_FMT.match(fmt):
        raise NativeParseError(-1)
    probes = (1.7976931348623157e308, -2.2250738585072014e-308,
              -1.2345678901234567e-5, float("inf"))
    return max(len(fmt % v) for v in probes) + 4


def format_coord(rowidx, colidx, vals, fmt: str = "%.17g") -> bytes:
    rowidx = _i64(rowidx)
    colidx = _i64(colidx)
    nnz = rowidx.size
    vals = None if vals is None else np.ascontiguousarray(vals, np.float64)
    idxw = (len(str(int(rowidx.max()) + 1)) + len(str(int(colidx.max()) + 1))
            if nnz else 2)
    est = idxw + 3 + (_fmt_width(fmt) if vals is not None else 0)
    cap = nnz * est + 128
    out = ctypes.create_string_buffer(cap)
    rc = _lib.acg_mtx_format_coord(
        nnz, _ptr(rowidx, _I64), _ptr(colidx, _I64),
        _ptr(vals, _F64) if vals is not None else None,
        fmt.encode(), out, cap)
    if rc < 0:
        raise NativeParseError(rc)
    return out.raw[:rc]


def format_array(vals, fmt: str = "%.17g") -> bytes:
    vals = np.ascontiguousarray(vals, np.float64).reshape(-1)
    cap = vals.size * (_fmt_width(fmt) + 2) + 128
    out = ctypes.create_string_buffer(cap)
    rc = _lib.acg_mtx_format_array(vals.size, _ptr(vals, _F64),
                                   fmt.encode(), out, cap)
    if rc < 0:
        raise NativeParseError(rc)
    return out.raw[:rc]


# ---- symmetric CSR assembly ---------------------------------------------

def sym_csr_from_coo(nrows: int, rowidx, colidx, vals):
    """COO -> packed-upper CSR (prowptr, pcolidx, pa); duplicates summed,
    mirrored full-storage input halved (SymCsrMatrix.from_coo semantics)."""
    rowidx = _i64(rowidx)
    colidx = _i64(colidx)
    vals = None if vals is None else np.ascontiguousarray(vals, np.float64)
    nnz = rowidx.size
    workkeys = np.empty(nnz, dtype=np.int64)
    workperm = np.empty(nnz, dtype=np.int64)
    mirrored = np.zeros(1, dtype=np.int32)
    pnnz = _lib.acg_sym_csr_count(nrows, nnz, _ptr(rowidx, _I64),
                                  _ptr(colidx, _I64), _ptr(workkeys, _I64),
                                  _ptr(workperm, _I64), _ptr(mirrored, _I32))
    if pnnz < 0:
        raise NativeParseError(pnnz)
    prowptr = np.empty(nrows + 1, dtype=np.int64)
    pcolidx = np.empty(pnnz, dtype=np.int64)
    pa = np.empty(pnnz, dtype=np.float64)
    if vals is None:
        vals = np.ones(nnz, dtype=np.float64)
    rc = _lib.acg_sym_csr_fill(nrows, nnz, pnnz, _ptr(workkeys, _I64),
                               _ptr(workperm, _I64), _ptr(vals, _F64),
                               int(mirrored[0]), _ptr(prowptr, _I64),
                               _ptr(pcolidx, _I64), _ptr(pa, _F64))
    if rc < 0:
        raise NativeParseError(rc)
    return prowptr, pcolidx, pa


def sym_csr_expand(nrows: int, prowptr, pcolidx, pa, epsilon: float = 0.0):
    """Packed upper CSR -> full-storage CSR (+ epsilon*I), sorted columns."""
    prowptr = _i64(prowptr)
    pcolidx = _i64(pcolidx)
    pa = np.ascontiguousarray(pa, np.float64)
    cap = 2 * pcolidx.size + (nrows if epsilon else 0)
    frowptr = np.empty(nrows + 1, dtype=np.int64)
    fcolidx = np.empty(max(cap, 1), dtype=np.int64)
    fa = np.empty(max(cap, 1), dtype=np.float64)
    rc = _lib.acg_sym_csr_expand(nrows, _ptr(prowptr, _I64),
                                 _ptr(pcolidx, _I64), _ptr(pa, _F64),
                                 float(epsilon), _ptr(frowptr, _I64),
                                 _ptr(fcolidx, _I64), _ptr(fa, _F64), cap)
    if rc < 0:
        raise NativeParseError(rc)
    return frowptr, fcolidx[:rc].copy(), fa[:rc].copy()


# ---- graph partitioning --------------------------------------------------

def graph_partition(nrows: int, frowptr, fcolidx, part, nparts: int):
    """One-pass subdomain construction.  Returns a dict of per-part counts
    and ragged arrays (see native/src/acg_core.h acg_pr_fill layout)."""
    frowptr = _i64(frowptr)
    fcolidx = _i64(fcolidx)
    part = np.ascontiguousarray(part, dtype=np.int32)
    handle = _lib.acg_graph_partition_run(
        nrows, _ptr(frowptr, _I64), _ptr(fcolidx, _I64), _ptr(part, _I32),
        nparts)
    if not handle:
        raise NativeParseError(-3)
    try:
        nowned = np.empty(nparts, dtype=np.int64)
        ninterior = np.empty(nparts, dtype=np.int64)
        nghost = np.empty(nparts, dtype=np.int64)
        nsend = np.empty(nparts, dtype=np.int64)
        _lib.acg_pr_counts(handle, _ptr(nowned, _I64), _ptr(ninterior, _I64),
                           _ptr(nghost, _I64), _ptr(nsend, _I64))
        global_ids = np.empty(int((nowned + nghost).sum()), dtype=np.int64)
        ghost_owner = np.empty(int(nghost.sum()), dtype=np.int32)
        send_part = np.empty(int(nsend.sum()), dtype=np.int32)
        send_gid = np.empty(int(nsend.sum()), dtype=np.int64)
        send_lidx = np.empty(int(nsend.sum()), dtype=np.int64)
        _lib.acg_pr_fill(handle, _ptr(global_ids, _I64),
                         _ptr(ghost_owner, _I32), _ptr(send_part, _I32),
                         _ptr(send_gid, _I64), _ptr(send_lidx, _I64))
    finally:
        _lib.acg_pr_free(handle)
    return dict(nowned=nowned, ninterior=ninterior, nghost=nghost,
                nsend=nsend, global_ids=global_ids, ghost_owner=ghost_owner,
                send_part=send_part, send_gid=send_gid, send_lidx=send_lidx)


# ---- host CG solver ------------------------------------------------------

def cg_solve(rowptr, colidx, vals, b, x0=None, maxits=100, res_atol=0.0,
             res_rtol=0.0, diff_atol=0.0, diff_rtol=0.0):
    """Native classic-CG solve over full-storage CSR (acg_cg_solve).

    Returns ``(x, r, niter, rnrm2, r0nrm2, dxnrm2, converged,
    indefinite)`` -- ``r`` is the final residual vector (for the
    caller's FP-exception scan) and ``indefinite`` reports the
    reference's (p, Ap) == 0 abort (``ACG_ERR_NOT_CONVERGED_
    INDEFINITE_MATRIX``, cg.c:304).  The C loop mirrors
    ``solvers.host_cg.HostCGSolver`` exactly (see native/src/cg.cpp),
    so the two host oracles cross-check each other.
    """
    rowptr = _i64(rowptr)
    colidx = _i64(colidx)
    vals = np.ascontiguousarray(vals, dtype=np.float64)
    b = np.ascontiguousarray(b, dtype=np.float64)
    x = (np.zeros_like(b) if x0 is None
         else np.array(x0, dtype=np.float64, copy=True))
    n = b.size
    # validate shapes BEFORE crossing into C: the native loop writes
    # x[0..n) and reads rowptr[0..n], trusting the caller
    if x.shape != (n,):
        raise ValueError(f"x0 has shape {x.shape}, need ({n},)")
    if rowptr.shape != (n + 1,):
        raise ValueError(f"rowptr has shape {rowptr.shape}, need ({n + 1},)")
    nnz = int(rowptr[-1])
    if colidx.size < nnz or vals.size < nnz:
        raise ValueError(f"colidx/vals have {colidx.size}/{vals.size} "
                         f"entries, rowptr ends at {nnz}")
    if nnz and (colidx[:nnz].min() < 0 or colidx[:nnz].max() >= n):
        raise ValueError("colidx out of range")
    niter = np.zeros(1, dtype=np.int32)
    out = np.zeros(3, dtype=np.float64)  # rnrm2, r0nrm2, dxnrm2
    r = np.zeros_like(b)
    rc = _lib.acg_cg_solve(
        n, _ptr(rowptr, _I64), _ptr(colidx, _I64), _ptr(vals, _F64),
        _ptr(b, _F64), _ptr(x, _F64), int(maxits),
        float(res_atol), float(res_rtol), float(diff_atol), float(diff_rtol),
        _ptr(niter, _I32), _ptr(out[0:], _F64), _ptr(out[1:], _F64),
        _ptr(out[2:], _F64), _ptr(r, _F64))
    if rc < 0:
        raise ValueError(f"acg_cg_solve: invalid input (code {rc})")
    return (x, r, int(niter[0]), float(out[0]), float(out[1]), float(out[2]),
            rc == 0, rc == 2)
