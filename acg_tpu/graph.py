"""Distributed graph partitioning core: subdomains and halo plans.

Rebuilds the reference's ``acg/graph.c`` (SURVEY.md component #6) and the
halo-plan construction of ``acg/halo.c:61-241``: given the sparsity pattern
of a symmetric matrix and a partition vector, build per-part subdomains
whose nodes are reordered **interior -> border -> ghost** (``graph.h:
199-243``), with per-neighbour send/recv lists derived from the border and
ghost sets.  This data-layout invariant is what enables communication/
computation overlap in every solver variant.

Differences from the reference, by design:
  * Single-controller: all subdomains are built on one host by vectorised
    numpy passes instead of MPI scatter of subgraphs (``graph.c:1529-1897``).
    The mesh shards the results (one subdomain per device coordinate).
  * Ghost nodes are grouped by owner part and sorted by global id within
    each group, so each neighbour's recv window is a contiguous slice of
    the ghost region; both sides order halo entries by global node id,
    which replaces the reference's (recipient, node-tag) radix sort
    (``halo.c:61-241``) as the agreement rule.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import scipy.sparse as sp

from acg_tpu.errors import AcgError, ErrorCode
from acg_tpu.io.mtxfile import IDX_DTYPE


@dataclasses.dataclass
class HaloPlan:
    """Per-part halo exchange plan (the ``acghalo`` struct role,
    ``halo.h:72-186``).

    ``send_parts[i]`` receives ``send_counts[i]`` owned values gathered at
    local indices ``send_idx[send_ptr[i]:send_ptr[i+1]]``; symmetrically
    ``recv_parts``/``recv_counts``/``recv_idx`` scatter received values into
    the ghost region.  Both sides enumerate entries sorted by global node
    id, so matching windows agree without a handshake.
    """

    send_parts: np.ndarray   # (nsend_neighbors,) int32
    send_counts: np.ndarray  # (nsend_neighbors,) int64
    send_ptr: np.ndarray     # (nsend_neighbors+1,)
    send_idx: np.ndarray     # (total_send,) local indices into owned region
    recv_parts: np.ndarray
    recv_counts: np.ndarray
    recv_ptr: np.ndarray
    recv_idx: np.ndarray     # (total_recv,) local indices (>= nowned)

    @property
    def total_send(self) -> int:
        return int(self.send_idx.size)

    @property
    def total_recv(self) -> int:
        return int(self.recv_idx.size)


@dataclasses.dataclass
class Subdomain:
    """One part's view of the partitioned problem (the per-rank
    ``acggraph`` + ``acgsymcsrmatrix`` pairing, ``graph.h:54-329``).

    Local node ordering is ``[interior | border | ghost]``; vectors
    conforming to this subdomain have ``nowned + nghost`` entries with the
    ghosts trailing (excluded from reductions).
    """

    part: int
    ninterior: int
    nborder: int
    nghost: int
    global_ids: np.ndarray       # (nowned+nghost,) local -> global
    ghost_owner: np.ndarray      # (nghost,) owning part of each ghost
    halo: HaloPlan
    # full-storage CSR blocks in local indices (built by partition_matrix):
    # owned x owned local block, and owned x ghost off-diagonal block
    # (the reference's f*/o* split, symcsrmatrix.h:249-292)
    A_local: sp.csr_matrix | None = None
    A_ghost: sp.csr_matrix | None = None
    # "ibg" = interior|border|ghost (the reference's invariant);
    # "natural" = owned nodes ascending by global id (bandwidth-preserving,
    # set by reorder_owned_natural) -- ninterior/nborder stay as counts but
    # no longer describe contiguous ranges
    owned_order: str = "ibg"

    @property
    def nowned(self) -> int:
        return self.ninterior + self.nborder

    @property
    def border_offset(self) -> int:
        return self.ninterior

    @property
    def ghost_offset(self) -> int:
        return self.nowned


def adjacency_from_symcsr(prowptr, pcolidx, nrows: int) -> sp.csr_matrix:
    """Full symmetric adjacency (pattern only) from packed upper CSR."""
    rows = np.repeat(np.arange(nrows, dtype=IDX_DTYPE), np.diff(prowptr))
    cols = np.asarray(pcolidx)
    off = rows != cols
    r = np.concatenate([rows[off], cols[off]])
    c = np.concatenate([cols[off], rows[off]])
    adj = sp.coo_matrix((np.ones(r.size, dtype=np.int8), (r, c)),
                        shape=(nrows, nrows)).tocsr()
    adj.sum_duplicates()
    adj.sort_indices()
    return adj


def partition_graph_nodes(full_csr: sp.csr_matrix, part: np.ndarray,
                          nparts: int) -> list[Subdomain]:
    """Build all subdomains (without matrix blocks) from a partition vector.

    The role of ``acggraph_partition`` (``graph.c:813-1452``): interface
    extraction, interior/border/ghost reordering, neighbour lists, and halo
    plan derivation (``graph.c:1898-1981``).  Dispatches to the native
    one-pass C++ partitioner (``native/src/graph.cpp``, O(nnz) independent
    of nparts) when available, else vectorised numpy whole-graph passes
    (O(n * nparts)).
    """
    from acg_tpu import _native
    if _native.available():
        try:
            return _partition_graph_nodes_native(full_csr, part, nparts)
        except _native.NativeParseError:
            pass  # fall through to the numpy path for the error message
    return _partition_graph_nodes_numpy(full_csr, part, nparts)


def _partition_graph_nodes_native(full_csr, part, nparts) -> list[Subdomain]:
    from acg_tpu import _native
    n = full_csr.shape[0]
    part = np.asarray(part)
    if part.size != n:
        raise AcgError(ErrorCode.INVALID_PARTITION,
                       f"partition vector has {part.size} entries, matrix has {n} rows")
    if n and (part.min() < 0 or part.max() >= nparts):
        raise AcgError(ErrorCode.INVALID_PARTITION,
                       f"part ids outside [0, {nparts})")
    res = _native.graph_partition(n, np.asarray(full_csr.indptr, IDX_DTYPE),
                                  np.asarray(full_csr.indices, IDX_DTYPE),
                                  part, nparts)
    gid_off = np.concatenate([[0], np.cumsum(res["nowned"] + res["nghost"])])
    ghost_off = np.concatenate([[0], np.cumsum(res["nghost"])])
    send_off = np.concatenate([[0], np.cumsum(res["nsend"])])
    subdomains = []
    for p in range(nparts):
        nowned = int(res["nowned"][p])
        nghost = int(res["nghost"][p])
        global_ids = res["global_ids"][gid_off[p]:gid_off[p + 1]]
        ghost_owner = res["ghost_owner"][ghost_off[p]:ghost_off[p + 1]]
        sp_p = res["send_part"][send_off[p]:send_off[p + 1]]
        send_idx = res["send_lidx"][send_off[p]:send_off[p + 1]]
        send_parts, send_counts = np.unique(sp_p, return_counts=True)
        send_ptr = np.concatenate([[0], np.cumsum(send_counts)]).astype(IDX_DTYPE)
        recv_parts, recv_counts = np.unique(ghost_owner, return_counts=True)
        recv_ptr = np.concatenate([[0], np.cumsum(recv_counts)]).astype(IDX_DTYPE)
        recv_idx = np.arange(nowned, nowned + nghost, dtype=IDX_DTYPE)
        halo = HaloPlan(send_parts=send_parts.astype(np.int32),
                        send_counts=send_counts.astype(IDX_DTYPE),
                        send_ptr=send_ptr, send_idx=send_idx,
                        recv_parts=recv_parts.astype(np.int32),
                        recv_counts=recv_counts.astype(IDX_DTYPE),
                        recv_ptr=recv_ptr, recv_idx=recv_idx)
        subdomains.append(Subdomain(
            part=p, ninterior=int(res["ninterior"][p]),
            nborder=nowned - int(res["ninterior"][p]), nghost=nghost,
            global_ids=global_ids, ghost_owner=ghost_owner, halo=halo))
    return subdomains


def _partition_graph_nodes_numpy(full_csr, part, nparts) -> list[Subdomain]:
    n = full_csr.shape[0]
    part = np.asarray(part)
    if part.size != n:
        raise AcgError(ErrorCode.INVALID_PARTITION,
                       f"partition vector has {part.size} entries, matrix has {n} rows")
    if part.min() < 0 or part.max() >= nparts:
        raise AcgError(ErrorCode.INVALID_PARTITION,
                       f"part ids outside [0, {nparts})")

    indptr, indices = full_csr.indptr, full_csr.indices
    row_of = np.repeat(np.arange(n, dtype=IDX_DTYPE), np.diff(indptr))
    col = indices.astype(IDX_DTYPE)
    rp, cp = part[row_of], part[col]
    cut = rp != cp  # inter-part edges

    # border nodes: any endpoint of a cut edge (on its owner's side)
    is_border = np.zeros(n, dtype=bool)
    is_border[row_of[cut]] = True

    # cut edge list (u owned by p, v owned by q != p): u is sent p->q,
    # v is a ghost of p owned by q.
    cut_u, cut_v = row_of[cut], col[cut]
    cut_p, cut_q = rp[cut], cp[cut]

    subdomains = []
    for p in range(nparts):
        owned = np.flatnonzero(part == p).astype(IDX_DTYPE)
        border_mask = is_border[owned]
        interior = owned[~border_mask]
        border = owned[border_mask]

        mine = cut_p == p
        # ghosts of p, grouped by owner part then global id
        gv, gq = cut_v[mine], cut_q[mine]
        ghost_order = np.unique(gq * (n + 1) + gv)
        ghost_owner = (ghost_order // (n + 1)).astype(np.int32)
        ghosts = (ghost_order % (n + 1)).astype(IDX_DTYPE)

        global_ids = np.concatenate([interior, border, ghosts])
        nowned = owned.size

        # send plan: (q, u) pairs with u owned by p adjacent to part q,
        # deduped, grouped by q, sorted by global id within each group
        su, sq = cut_u[mine], cut_q[mine]
        send_order = np.unique(sq * (n + 1) + su)
        send_q = (send_order // (n + 1)).astype(np.int32)
        send_u = (send_order % (n + 1)).astype(IDX_DTYPE)
        send_parts, send_counts = np.unique(send_q, return_counts=True)
        send_ptr = np.concatenate([[0], np.cumsum(send_counts)]).astype(IDX_DTYPE)
        # map global send nodes to local indices (all are border nodes)
        g2l = np.full(n, -1, dtype=IDX_DTYPE)
        g2l[global_ids] = np.arange(global_ids.size, dtype=IDX_DTYPE)
        send_idx = g2l[send_u]

        recv_parts, recv_counts = np.unique(ghost_owner, return_counts=True)
        recv_ptr = np.concatenate([[0], np.cumsum(recv_counts)]).astype(IDX_DTYPE)
        recv_idx = np.arange(nowned, nowned + ghosts.size, dtype=IDX_DTYPE)

        halo = HaloPlan(send_parts=send_parts,
                        send_counts=send_counts.astype(IDX_DTYPE),
                        send_ptr=send_ptr, send_idx=send_idx,
                        recv_parts=recv_parts,
                        recv_counts=recv_counts.astype(IDX_DTYPE),
                        recv_ptr=recv_ptr, recv_idx=recv_idx)
        subdomains.append(Subdomain(part=p, ninterior=interior.size,
                                    nborder=border.size, nghost=ghosts.size,
                                    global_ids=global_ids,
                                    ghost_owner=ghost_owner, halo=halo))
    return subdomains


def partition_matrix(full_csr: sp.csr_matrix, part: np.ndarray,
                     nparts: int,
                     owned_parts=None) -> list[Subdomain]:
    """Build subdomains including local/off-diagonal matrix blocks.

    The ``f*``/``o*`` full-storage split of ``acgsymcsrmatrix_dsymv_init``
    (``symcsrmatrix.c:760-862``): for each part, an owned x owned CSR block
    and an owned x ghost CSR block, both in local indices, so the
    distributed SpMV is ``y = A_local x_owned + A_ghost x_ghost`` with the
    ghost gather supplied by the halo exchange.

    ``owned_parts`` (multi-controller): build matrix blocks only for the
    listed parts; the others keep ``A_local is None``.  The subdomain
    *structure* (node sets, halo plans) is still built for every part --
    it is O(nnz) total and every controller needs the global plan -- but
    the per-part block assembly and its memory are restricted to the
    parts this controller's devices own (the role of the reference's
    root-rank-assembles + scatter, ``graph.c:1529-1897``, with
    "every controller is the root of its own parts").
    """
    subs = partition_graph_nodes(full_csr, part, nparts)
    n = full_csr.shape[0]
    coo = full_csr.tocoo()
    part = np.asarray(part)
    rp = part[coo.row]
    owned_set = None if owned_parts is None else set(int(p) for p in owned_parts)
    for s in subs:
        if owned_set is not None and s.part not in owned_set:
            continue
        g2l = np.full(n, -1, dtype=IDX_DTYPE)
        g2l[s.global_ids] = np.arange(s.global_ids.size, dtype=IDX_DTYPE)
        mine = rp == s.part
        r, c, v = coo.row[mine], coo.col[mine], coo.data[mine]
        lr, lc = g2l[r], g2l[c]
        if (lr < 0).any() or (lc < 0).any():
            raise AcgError(ErrorCode.INVALID_PARTITION,
                           "matrix entry references node outside subdomain closure")
        local = lc < s.nowned
        s.A_local = sp.coo_matrix((v[local], (lr[local], lc[local])),
                                  shape=(s.nowned, s.nowned)).tocsr()
        s.A_ghost = sp.coo_matrix((v[~local], (lr[~local], lc[~local] - s.nowned)),
                                  shape=(s.nowned, max(s.nghost, 1))).tocsr()
        s.A_local.sort_indices()
        s.A_ghost.sort_indices()
    return subs


def subdomain_from_row_slice(rowidx, colidx, vals, bounds,
                             part: int) -> Subdomain:
    """Build ONE part's subdomain from ONLY its own rows.

    Inputs are the FULL-STORAGE entries of rows ``[bounds[part],
    bounds[part+1])`` of a structurally symmetric matrix under a
    contiguous band partition (``bounds``: nparts+1 ascending row
    boundaries) -- exactly what :func:`acg_tpu.io.mtxfile.
    read_mtx_row_range` returns for an ``mtx2bin --expand`` file.

    This restores the reference's only-local-data-per-rank property
    (``acggraph_partition`` per-rank construction + ``acggraph_scatter``,
    ``graph.c:813-1897``) without any root rank: structural symmetry
    makes the send side locally derivable (my row i couples ghost j of
    part q  <=>  q's row j couples my i, so "q will ask for i" is
    visible from my own rows).  Layout matches what
    ``partition_graph_nodes`` + ``reorder_owned_natural`` produce for
    the same band partition: owned rows ascending (natural), ghosts
    grouped by owner ascending by global id, send windows sorted by
    global id.
    """
    bounds = np.asarray(bounds, dtype=np.int64)
    lo, hi = int(bounds[part]), int(bounds[part + 1])
    nowned = hi - lo
    rowidx = np.asarray(rowidx)
    colidx = np.asarray(colidx)
    vals = np.asarray(vals)
    if rowidx.size and (rowidx.min() < lo or rowidx.max() >= hi):
        raise AcgError(ErrorCode.INVALID_PARTITION,
                       "row slice contains rows outside the band")

    outside = (colidx < lo) | (colidx >= hi)
    # ghosts ascending by global id; for band partitions owner order ==
    # id order, so this is also grouped-by-owner ascending
    ghosts = np.unique(colidx[outside]).astype(IDX_DTYPE)
    ghost_owner = (np.searchsorted(bounds, ghosts, side="right") - 1
                   ).astype(np.int32)
    nghost = ghosts.size

    # send plan: (q, i) pairs deduped, grouped by q, ascending i
    s_i = rowidx[outside]
    s_q = (np.searchsorted(bounds, colidx[outside], side="right") - 1)
    key = np.unique(s_q.astype(np.int64) * (bounds[-1] + 1) + s_i)
    send_q = (key // (bounds[-1] + 1)).astype(np.int32)
    send_i = (key % (bounds[-1] + 1)).astype(IDX_DTYPE)
    send_parts, send_counts = np.unique(send_q, return_counts=True)
    send_ptr = np.concatenate([[0], np.cumsum(send_counts)]).astype(IDX_DTYPE)
    recv_parts, recv_counts = np.unique(ghost_owner, return_counts=True)
    recv_ptr = np.concatenate([[0], np.cumsum(recv_counts)]).astype(IDX_DTYPE)
    halo = HaloPlan(send_parts=send_parts.astype(np.int32),
                    send_counts=send_counts.astype(IDX_DTYPE),
                    send_ptr=send_ptr,
                    send_idx=(send_i - lo).astype(IDX_DTYPE),
                    recv_parts=recv_parts.astype(np.int32),
                    recv_counts=recv_counts.astype(IDX_DTYPE),
                    recv_ptr=recv_ptr,
                    recv_idx=np.arange(nowned, nowned + nghost,
                                       dtype=IDX_DTYPE))

    # matrix blocks in local indices (owned rows natural ascending)
    lr = (rowidx - lo).astype(IDX_DTYPE)
    inside = ~outside
    A_local = sp.coo_matrix(
        (vals[inside], (lr[inside], (colidx[inside] - lo))),
        shape=(nowned, nowned)).tocsr()
    gcol = np.searchsorted(ghosts, colidx[outside])
    A_ghost = sp.coo_matrix(
        (vals[outside], (lr[outside], gcol)),
        shape=(nowned, max(nghost, 1))).tocsr()
    A_local.sort_indices()
    A_ghost.sort_indices()

    border = np.zeros(nowned, dtype=bool)
    border[lr[outside]] = True
    nborder = int(border.sum())
    global_ids = np.concatenate([np.arange(lo, hi, dtype=IDX_DTYPE), ghosts])
    return Subdomain(part=part, ninterior=nowned - nborder,
                     nborder=nborder, nghost=nghost,
                     global_ids=global_ids, ghost_owner=ghost_owner,
                     halo=halo, A_local=A_local, A_ghost=A_ghost,
                     owned_order="natural")


@dataclasses.dataclass
class BandStub:
    """Placeholder for a part whose data lives on ANOTHER controller in
    the local-read flow: carries only the analytically-known structure
    (band size); the matrix blocks and halo plan are None and every
    consumer that needs them fills that part's device shards on its
    owning controller instead."""

    part: int
    nowned_: int
    A_local = None
    A_ghost = None
    halo = None
    nghost = 0
    owned_order = "natural"

    @property
    def nowned(self) -> int:
        return self.nowned_


def reorder_owned_natural(subs: list[Subdomain]) -> list[Subdomain]:
    """Reorder each subdomain's owned nodes into ascending global id, in
    place (ghosts untouched).

    The reference's interior|border|ghost layout trades row locality for a
    contiguous border range; on TPU the opposite trade wins: with owned
    rows in global (natural/RCM) order, a contiguous partition of a banded
    matrix keeps every local diagonal block banded, enabling gather-free
    DIA SpMV -- measured ~30x faster than the ELL gather path
    (``ops/spmv.py``).  The halo plan stays valid because send windows are
    keyed by *global* id order (only the local indices are remapped), and
    scatter/gather go through ``global_ids``.
    """
    for s in subs:
        if s.owned_order == "natural":
            continue
        owned = s.global_ids[: s.nowned]
        perm = np.argsort(owned, kind="stable")   # new local -> old local
        inv = np.empty_like(perm)
        inv[perm] = np.arange(perm.size, dtype=perm.dtype)
        s.global_ids[: s.nowned] = owned[perm]
        s.halo.send_idx = inv[s.halo.send_idx].astype(s.halo.send_idx.dtype)
        if s.A_local is not None:
            s.A_local = s.A_local[perm][:, perm].tocsr()
            s.A_local.sort_indices()
        if s.A_ghost is not None:
            s.A_ghost = s.A_ghost[perm].tocsr()
            s.A_ghost.sort_indices()
        s.owned_order = "natural"
    return subs


def halo_exchange_host(subs: list[Subdomain], xs: list[np.ndarray]) -> None:
    """Host-side halo exchange over subdomain vectors, in place.

    The role of ``acghalo_exchange`` (``halo.c:687``) for the host
    reference path: gather each part's send entries, deliver into the
    matching ghost windows.  Used by the distributed host SpMV oracle and
    as the semantics model for the device implementations.
    """
    packed = {}
    for i, s in enumerate(subs):
        h = s.halo
        for j, q in enumerate(h.send_parts):
            idx = h.send_idx[h.send_ptr[j]:h.send_ptr[j + 1]]
            packed[(s.part, int(q))] = xs[i][idx]
    # deliver
    for i, s in enumerate(subs):
        h = s.halo
        for j, q in enumerate(h.recv_parts):
            window = h.recv_idx[h.recv_ptr[j]:h.recv_ptr[j + 1]]
            buf = packed[(int(q), s.part)]
            if buf.size != window.size:
                raise AcgError(ErrorCode.INVALID_PARTITION,
                               f"halo window mismatch {q}->{s.part}: "
                               f"{buf.size} != {window.size}")
            xs[i][window] = buf


def dsymv_dist_host(subs: list[Subdomain], xs: list[np.ndarray]) -> list[np.ndarray]:
    """Distributed host SpMV (the ``acgsymcsrmatrix_dsymvmpi`` role,
    ``symcsrmatrix.c:1353-1397``): halo exchange then local + offdiag SpMV."""
    halo_exchange_host(subs, xs)
    out = []
    for s, x in zip(subs, xs):
        y = s.A_local @ x[: s.nowned]
        if s.nghost:
            y = y + s.A_ghost @ x[s.nowned: s.nowned + s.nghost]
        out.append(y)
    return out


def comm_matrix(subs: list[Subdomain], nparts: int) -> np.ndarray:
    """Part-to-part communication volume matrix (``--output-comm-matrix``,
    ``cuda/acg-cuda.c:1712-1780``)."""
    M = np.zeros((nparts, nparts), dtype=np.int64)
    for s in subs:
        h = s.halo
        for q, cnt in zip(h.send_parts, h.send_counts):
            M[s.part, q] = cnt
    return M


def scatter_vector(subs: list[Subdomain], x_global: np.ndarray,
                   include_ghosts: bool = False) -> list[np.ndarray]:
    """Split a global vector into subdomain-conforming vectors
    (the ``acgvector_usga`` + ``acgvector_scatter`` pipeline,
    ``cuda/acg-cuda.c:1987-2059``)."""
    out = []
    for s in subs:
        v = np.zeros(s.nowned + s.nghost, dtype=x_global.dtype)
        v[: s.nowned] = x_global[s.global_ids[: s.nowned]]
        if include_ghosts:
            v[s.nowned:] = x_global[s.global_ids[s.nowned:]]
        out.append(v)
    return out


def gather_vector(subs: list[Subdomain], xs: list[np.ndarray],
                  n: int) -> np.ndarray:
    """Inverse of :func:`scatter_vector`: owned entries back to global order
    (the distributed solution write, ``mtxfile_fwrite_mpi_double`` role)."""
    out = np.zeros(n, dtype=xs[0].dtype)
    for s, x in zip(subs, xs):
        out[s.global_ids[: s.nowned]] = x[: s.nowned]
    return out
