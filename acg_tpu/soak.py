"""Soak driver: N repeated solves with latency/drift observability.

A single timed solve says nothing about a SERVICE: the fleet-scale
failure modes are latency drift (a leaking cache, a slowly contending
neighbour, thermal throttling) and jitter in the tail, which the
reduction-pipelining literature (arXiv:1905.06850) identifies -- not
mean cost -- as the scaling killer.  This driver runs ``nsolves``
repeated solves of one system, feeds every solve into the process-wide
metrics registry (:mod:`acg_tpu.metrics`), reports p50/p95/p99 solve
latency and iterations-to-converge FROM the registry histograms (so
the soak report and a Prometheus scrape of the same run agree), and
arms an EWMA drift detector over the measured latencies:

* baseline = median of the first ``BASELINE_FRACTION`` of solves
  (median, so the first solve's compile spike cannot poison it);
* after the baseline window, ``ewma = (1-alpha)*ewma + alpha*latency``;
* drift trips when ``ewma / baseline > 1 + threshold_pct/100`` --
  a structured ``drift`` event lands in ``SolverStats.events``
  (the ``--stats-json`` twin) and, under ``--fail-on-drift PCT``,
  the CLI exits nonzero (exit code 7).

The fault injector's ``solve:slow@K:secs=S`` site dilates every solve
from index K onward inside the timed window
(:func:`acg_tpu.faults.maybe_slow_solve`), so the detector's trip path
is exercisable deterministically end-to-end.

The driver never touches the compiled programs: it is a host loop
around the solver's own ``solve()`` -- the per-solve latency includes
dispatch, which is exactly what a serving fleet experiences.
"""

from __future__ import annotations

import math
import sys
import time

from acg_tpu import metrics, observatory, telemetry, tracing

# EWMA smoothing for the drift detector: 0.2 remembers ~the last 10
# solves -- slow enough to ride out one contended solve, fast enough to
# trip within a couple of windows of a real degradation
EWMA_ALPHA = 0.2
# leading fraction of the run that defines the latency baseline
BASELINE_FRACTION = 0.2
# minimum solves in the baseline window (a --soak 5 run still gets a
# median-of-3 baseline, not a single-sample one)
BASELINE_MIN = 3
# warning threshold when no --fail-on-drift gate is set
DEFAULT_DRIFT_PCT = 50.0
# CLI exit code for a tripped --fail-on-drift gate (the process-wide
# contract lives in errors.ExitCode; --buildinfo renders the table)
from acg_tpu.errors import ExitCode as _ExitCode

DRIFT_EXIT_CODE = int(_ExitCode.DRIFT)


class DriftDetector:
    """EWMA latency-drift detector with a median baseline window."""

    def __init__(self, nsolves: int, threshold_pct: float):
        self.threshold_pct = float(threshold_pct)
        self.nbaseline = max(BASELINE_MIN,
                             int(nsolves * BASELINE_FRACTION))
        self._window: list[float] = []
        self.baseline: float | None = None
        self.ewma: float | None = None
        self.tripped_at: int | None = None

    def update(self, i: int, latency: float) -> bool:
        """Feed solve ``i``'s latency; True the first time drift trips."""
        if len(self._window) < self.nbaseline:
            self._window.append(float(latency))
            if len(self._window) == self.nbaseline:
                self.baseline = sorted(self._window)[
                    len(self._window) // 2]
                self.ewma = self.baseline
            return False
        self.ewma = (1.0 - EWMA_ALPHA) * self.ewma \
            + EWMA_ALPHA * float(latency)
        if metrics.armed():
            metrics.DRIFT_RATIO.set(self.ratio)
        if (self.tripped_at is None and self.baseline > 0
                and self.ratio > 1.0 + self.threshold_pct / 100.0):
            self.tripped_at = int(i)
            return True
        return False

    @property
    def ratio(self) -> float:
        if not self.baseline or self.ewma is None:
            return 1.0
        return self.ewma / self.baseline

    def to_dict(self) -> dict:
        return {
            "baseline_s": self.baseline,
            "ewma_s": self.ewma,
            "ratio": round(self.ratio, 4),
            "threshold_pct": self.threshold_pct,
            "tripped": self.tripped_at is not None,
            "tripped_at_solve": self.tripped_at,
            "baseline_solves": self.nbaseline,
            "ewma_alpha": EWMA_ALPHA,
        }


def gate_is_vacuous(nsolves: int) -> bool:
    """True when a drift gate over ``nsolves`` solves could never trip:
    the baseline window consumes the whole run, so no solve is ever
    evaluated against it.  Callers wiring ``fail_on_drift`` must refuse
    such a run -- a gate that inspects nothing greens CI silently."""
    n = int(nsolves)
    return n <= max(BASELINE_MIN, int(n * BASELINE_FRACTION))


def _percentiles(hist) -> dict:
    out = {}
    for q, name in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
        v = hist.quantile(q)
        out[name] = None if (v is None or math.isnan(v)) else v
    return out


def run_soak(solver, b, *, nsolves: int, x0=None, criteria=None,
             fail_on_drift: float | None = None,
             first_solve_kwargs: dict | None = None,
             solve_kwargs: dict | None = None,
             progress_every: int = 0, what: str = "soak"):
    """Run ``nsolves`` repeated solves and return ``(x, report)``.

    ``x`` is the last solve's solution (all solves share ``b``/``x0``,
    so any of them is THE solution; the last is returned so the CLI's
    output path is unchanged).  ``report`` is the JSON-able ``soak``
    section: per-run percentiles from the registry histograms, the
    drift verdict, and the registry's own solve counters.

    ``first_solve_kwargs`` ride only solve 0 (warmup, which absorbs the
    compile); ``solve_kwargs`` ride every solve.  Arms the metrics
    layer -- the soak driver IS a metrics consumer by definition.
    """
    from acg_tpu import faults

    if nsolves < 1:
        raise ValueError(f"soak needs nsolves >= 1, got {nsolves}")
    if fail_on_drift is not None and gate_is_vacuous(nsolves):
        raise ValueError(
            f"fail_on_drift is vacuous at nsolves={nsolves}: the "
            f"baseline window consumes the whole run, so the gate "
            f"could never trip (need nsolves > "
            f"{max(BASELINE_MIN, int(nsolves * BASELINE_FRACTION))})")
    metrics.arm()
    threshold = (fail_on_drift if fail_on_drift is not None
                 else DEFAULT_DRIFT_PCT)
    det = DriftDetector(nsolves, threshold)
    st = solver.stats
    kwargs = dict(solve_kwargs or {})
    # run-local histograms with the SAME bucket ladders as the
    # process-wide ones: the registry accumulates for process life (a
    # bench process may soak several configurations back to back), so
    # THIS run's percentiles come from a private pair while every
    # observation still lands in the global registry via the solvers'
    # own record_solve hooks
    local = metrics.Registry()
    lat_hist = local.histogram("soak_solve_seconds",
                               buckets=metrics.SOLVE_SECONDS_BUCKETS)
    it_hist = local.histogram("soak_solve_iterations",
                              buckets=metrics.ITERATION_BUCKETS)
    t_run0 = time.perf_counter()
    latencies_max = 0.0
    # numerical-health tier: per-solve audit gaps (solver.stats.health,
    # present when --audit-every is armed) tracked ALONGSIDE latency --
    # a serving fleet's accuracy can drift (accumulating operator
    # updates, thermal-driven recompiles) just like its latency
    gaps: list[float] = []
    # batched tier (acg_tpu.solvers.batched): per-RHS iteration counts
    # and EFFECTIVE latencies across the run -- a batch completes
    # together, but each RHS's share of the wall clock is its frozen-at
    # iteration over the slowest RHS's, which is what a per-request SLA
    # on a coalescing service actually observes
    rhs_iters: list[int] = []
    rhs_lats: list[float] = []
    rhs_n = 0
    x = None
    for i in range(nsolves):
        kw = dict(kwargs)
        if i == 0 and first_solve_kwargs:
            kw.update(first_solve_kwargs)
        t0 = time.perf_counter()
        t0_wall = time.time()
        # the injected-slowdown site (solve:slow@K:secs=S) sleeps
        # INSIDE the timed window -- a deterministic stand-in for
        # contention/throttling that the drift detector must catch
        faults.maybe_slow_solve(i)
        x = solver.solve(b, x0=x0, criteria=criteria, **kw)
        lat = time.perf_counter() - t0
        # timeline tier: an INDEXED span per soak solve (the solver's
        # own "solve" phase spans are indistinguishable across N
        # repeats; a drift timeline needs to say which solve slowed)
        tracing.record_span(f"{what}[{i}]", t0_wall, t0_wall + lat,
                            cat="chunk", index=i)
        lat_hist.observe(lat)
        it_hist.observe(max(int(st.niterations), 0))
        latencies_max = max(latencies_max, lat)
        g = (st.health or {}).get("gap_last")
        if g is not None and math.isfinite(float(g)):
            gaps.append(float(g))
        batch = st.batch or {}
        if batch.get("nrhs", 0) >= 1 and batch.get("iterations"):
            rhs_n = int(batch["nrhs"])
            its_b = [int(v) for v in batch["iterations"]]
            kmax = max(max(its_b), 1)
            rhs_iters.extend(its_b)
            rhs_lats.extend(lat * it / kmax for it in its_b)
        # live-observatory tier: per-solve queue progress for the
        # status endpoint (no-op disarmed) and the SLO verdict for
        # this solve (no-op without declared objectives; breaches
        # land as structured events + acg_slo_* metrics)
        observatory.note_soak_solve(i, nsolves, lat)
        observatory.slo_observe(st, latency=lat,
                                iterations=int(st.niterations),
                                gap=g)
        if det.update(i, lat):
            msg = (f"latency drift: EWMA {det.ewma:.6f}s is "
                   f"{(det.ratio - 1.0) * 100.0:+.1f}% over the "
                   f"baseline {det.baseline:.6f}s at solve {i} "
                   f"(threshold {threshold:g}%)")
            # record_event routes to acg_events_total{kind=drift} too
            telemetry.record_event(st, "drift", msg)
            sys.stderr.write(f"acg-tpu: {what}: WARNING: {msg}\n")
        if progress_every and (i + 1) % progress_every == 0:
            sys.stderr.write(
                f"acg-tpu: {what}: {i + 1}/{nsolves} solves, "
                f"p50 {lat_hist.quantile(0.5):.6f}s, "
                f"drift ratio {det.ratio:.3f}\n")
    report = {
        "nsolves": int(nsolves),
        "wall_seconds": time.perf_counter() - t_run0,
        "latency": {**_percentiles(lat_hist), "max": latencies_max},
        "iterations": _percentiles(it_hist),
        "drift": det.to_dict(),
    }
    if rhs_iters:
        # per-RHS view of a batched soak (stats schema /9): quantiles
        # over every (solve, rhs) pair of the run
        def _q(vals, q):
            s = sorted(vals)
            return s[min(int(q * len(s)), len(s) - 1)]

        report["per_rhs"] = {
            "nrhs": rhs_n,
            "iterations": {"p50": _q(rhs_iters, 0.5),
                           "p95": _q(rhs_iters, 0.95),
                           "p99": _q(rhs_iters, 0.99)},
            "latency": {"p50": _q(rhs_lats, 0.5),
                        "p95": _q(rhs_lats, 0.95),
                        "p99": _q(rhs_lats, 0.99)},
        }
    if gaps:
        # accuracy-drift view of the run: how the audited true-residual
        # gap moved across repeated solves (the latency drift gate's
        # numerical twin; warn-only -- the per-solve threshold gate
        # already owns the hard verdict)
        report["gap"] = {
            "first": gaps[0], "last": gaps[-1], "max": max(gaps),
            "ratio": (gaps[-1] / gaps[0]) if gaps[0] > 0 else None,
        }
        if gaps[0] > 0 and gaps[-1] / gaps[0] > 1.0 + threshold / 100.0:
            msg = (f"residual-gap drift: last audit gap {gaps[-1]:.3e} "
                   f"is {(gaps[-1] / gaps[0] - 1.0) * 100.0:+.1f}% over "
                   f"the first solve's {gaps[0]:.3e} "
                   f"(threshold {threshold:g}%)")
            telemetry.record_event(st, "gap-drift", msg)
            sys.stderr.write(f"acg-tpu: {what}: WARNING: {msg}\n")
    st.soak = report
    return x, report


def gate_exit_code(report: dict | None,
                   fail_on_drift: float | None) -> int:
    """The ``--fail-on-drift`` verdict for a completed soak run: 0, or
    :data:`DRIFT_EXIT_CODE` when the gate is set and drift tripped."""
    if (report is None or fail_on_drift is None
            or not report.get("drift", {}).get("tripped")):
        return 0
    return DRIFT_EXIT_CODE
