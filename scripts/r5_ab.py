"""Round-5 targeted same-window A/Bs (VERDICT r4 items 3, 4, 5).

Each comparison interleaves its two cases A,B,A,B,... so both sides
share one contention window (the round-2 methodology that established
nparts=1 parity), and reports the median ratio plus before/after probe
readings.  Selectable via --only so a flaky tunnel cannot take out the
whole set:

  * ``dist1``   -- the nparts=1 distributed program vs the single-chip
    solver on the flagship 2D config: LADDER_r04 recorded 0.07x where
    round 2 measured 0.96x; adjudicate regression vs contention
    artifact (VERDICT item 4).
  * ``mixed3d`` -- mixed vs f32 on the 3D clustered-kernel path (256^3
    by default, 512^3 with --big): the mixed tier lost at 3D two
    rounds running despite a ~1.3x traffic model (VERDICT item 5).
  * ``bell``    -- distributed binned-ELL local blocks vs plain-ELL
    blocks on the 500k power-law workload, nparts=1 mesh (VERDICT
    item 3's measurement half).

Appends JSON rows to QUIET_AB.jsonl like quiet_ab.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

ROOT = __file__.rsplit("/", 2)[0]
sys.path.insert(0, ROOT)
RECORD = os.path.join(ROOT, "QUIET_AB.jsonl")


def _timer(solver, b, its, host_result=None):
    """One timed unbounded solve at ``its`` iterations; two-point
    corrected when the completion signal is broken (bench rationale)."""
    from acg_tpu._platform import block_until_ready_works
    from acg_tpu.solvers.stats import StoppingCriteria

    kw = {} if host_result is None else {"host_result": host_result}

    def timed(n):
        solver.stats.tsolve = 0.0
        solver.solve(b, criteria=StoppingCriteria(maxits=n), **kw)
        return solver.stats.tsolve

    timed(50)  # compile + warm
    best = timed(its)
    if not block_until_ready_works():
        t_short = timed(max(its // 4, 1))
        dt = best - t_short
        n_dt = its - max(its // 4, 1)
        if dt > 0 and best / (dt / n_dt * its) < 20:
            best = dt / n_dt * its
    return its / best


def _chained_rate(run, k_long: int) -> float:
    """Rate (units/s) of a chained device program: ``run(k)`` executes
    and syncs a k-step chain.  Under a broken completion signal the
    dispatch round-trip is subtracted by a two-point difference, with
    the same <20x plausibility guard as ``bench._time_solver`` -- a
    contention spike in the short leg can shrink the difference
    arbitrarily and record an unboundedly inflated rate."""
    from acg_tpu._platform import block_until_ready_works

    k_short = max(k_long // 4, 1)
    run(k_short)  # compile + warm both sizes
    run(k_long)
    t0 = time.time()
    run(k_long)
    t_long = time.time() - t0
    raw = k_long / t_long
    if block_until_ready_works():
        return raw
    t0 = time.time()
    run(k_short)
    t_short = time.time() - t0
    dt = t_long - t_short
    if dt > 0:
        corrected = (k_long - k_short) / dt
        if corrected / raw < 20:
            return corrected
    return raw


def _emit_interleaved(name, rate_a, rate_b, label_a, label_b, pairs,
                      unit="spmv/s", extra=None):
    """Interleave two rate callables A,B,A,B,... in one contention
    window; emit + append the median-ratio row (shared by the
    chained-SpMV A/Bs)."""
    import numpy as np

    from bench import bandwidth_probe_gbs

    try:
        bw0 = bandwidth_probe_gbs(refresh=True)
    except Exception:
        bw0 = 0.0
    va, vb = [], []
    for _ in range(pairs):
        va.append(rate_a())
        vb.append(rate_b())
    try:
        bw1 = bandwidth_probe_gbs(refresh=True)
    except Exception:
        bw1 = 0.0
    ra, rb = float(np.median(va)), float(np.median(vb))
    row = {"ab": name, label_a: round(ra, 2), label_b: round(rb, 2),
           "ratio": round(ra / rb, 3), "unit": unit,
           "bw_gbs": round(bw0, 1), "bw_gbs_after": round(bw1, 1),
           "pairs": pairs, "ts": round(time.time(), 1)}
    if extra:
        row.update(extra)
    from acg_tpu._platform import block_until_ready_works
    if not block_until_ready_works():
        row["block_sync_broken"] = True
    print(json.dumps(row))
    sys.stdout.flush()
    with open(RECORD, "a") as f:
        f.write(json.dumps(row) + "\n")
    return row


def _ab_row(name, mk_a, mk_b, label_a, label_b, b, its, pairs,
            host_result=None, extra=None):
    """Interleaved whole-solve A/B: one fresh solver per rep per side."""
    return _emit_interleaved(
        name,
        lambda: _timer(mk_a(), b, its, host_result),
        lambda: _timer(mk_b(), b, its, host_result),
        label_a, label_b, pairs, unit="iters/s", extra=extra)


def ab_dist1(pairs):
    import numpy as np
    import jax.numpy as jnp

    from acg_tpu.io.generators import poisson2d_coo
    from acg_tpu.matrix import SymCsrMatrix
    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
    from acg_tpu.partition import partition_rows
    from acg_tpu.solvers.jax_cg import JaxCGSolver

    r, c, v, N = poisson2d_coo(2048)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    b = np.ones(N, dtype=np.float32)
    part = partition_rows(csr, 1, seed=0)
    prob = DistributedProblem.build(csr, part, 1, dtype=jnp.float32)
    A = device_matrix_from_csr(csr, dtype=jnp.float32)
    _ab_row("dist1_vs_single_2d2048_f32",
            lambda: DistCGSolver(prob, kernels="xla"),
            lambda: JaxCGSolver(A, kernels="xla"),
            "dist1", "single", b, 1000, pairs,
            extra={"local_format": prob.local.format})


def ab_mixed3d(pairs, side):
    import jax
    import jax.numpy as jnp

    from acg_tpu.io.generators import poisson_dia_device
    from acg_tpu.ops.spmv import DiaMatrix
    from acg_tpu.solvers.jax_cg import JaxCGSolver

    mats = {}
    for name, dt, vdt in (("f32", jnp.float32, jnp.float32),
                          ("mixed", jnp.bfloat16, jnp.float32)):
        planes, offsets, N = poisson_dia_device(side, 3, dtype=dt)
        mats[name] = DiaMatrix(data=tuple(planes), offsets=offsets,
                               nrows=N, ncols_padded=N)
    b = jnp.ones(mats["f32"].nrows, dtype=jnp.float32)
    its = 400 if side >= 512 else 1000
    row = _ab_row(f"mixed_vs_f32_3d{side}_dia",
                  lambda: JaxCGSolver(mats["mixed"], kernels="auto",
                                      vector_dtype=jnp.float32),
                  lambda: JaxCGSolver(mats["f32"], kernels="auto"),
                  "mixed", "f32", b, its, pairs, host_result=False,
                  extra={"side": side})
    return row


def ab_roll3d(pairs, side):
    """Clustered-Pallas vs xla-roll at the north-star 3D size: the
    sharded route pins its SpMV to the roll formulation (cli.py), so
    this gap IS the cost of that pin on one chip (VERDICT item 7 --
    'measure and document', with the shard_map wrapper as the follow-up
    if the gap is real)."""
    import jax.numpy as jnp

    from acg_tpu.io.generators import poisson_dia_device
    from acg_tpu.ops.spmv import DiaMatrix
    from acg_tpu.solvers.jax_cg import JaxCGSolver

    planes, offsets, N = poisson_dia_device(side, 3, dtype=jnp.float32)
    A = DiaMatrix(data=tuple(planes), offsets=offsets,
                  nrows=N, ncols_padded=N)
    b = jnp.ones(N, dtype=jnp.float32)
    its = 400 if side >= 512 else 1000
    _ab_row(f"pallas_vs_roll_3d{side}_f32_dia",
            lambda: JaxCGSolver(A, kernels="pallas"),
            lambda: JaxCGSolver(A, kernels="xla-roll"),
            "pallas", "roll", b, its, pairs, host_result=False,
            extra={"side": side})


def ab_proll(pairs, side):
    """xla-roll vs pallas-roll on the SHARDED solver itself (nparts=1
    here; the shard-level program is identical at any nparts up to the
    ppermute halo): the decision measurement for the sharded route's
    kernel pin (VERDICT item 7)."""
    import jax.numpy as jnp

    from acg_tpu.parallel.sharded_dia import build_sharded_poisson_solver

    s_pal = build_sharded_poisson_solver(side, 3, nparts=1,
                                         kernels="pallas-roll")
    # drop the clean-plane set: the bench only runs the programs (which
    # consume the padded twin), and at 512^3 a third ~3.8 GB plane set
    # would push the interleaved pair toward OOM.  spmv_flops over the
    # padded planes counts the same nonzeros (padding is zeros).
    s_pal.A = s_pal._A_program
    s_xla = build_sharded_poisson_solver(side, 3, nparts=1)
    b = s_xla.ones_b()
    its = 400 if side >= 512 else 1000
    _ab_row(f"sharded_pallasroll_vs_xlaroll_3d{side}",
            lambda: s_pal, lambda: s_xla,
            "pallas_roll", "xla_roll", b, its, pairs, host_result=False,
            extra={"side": side})


def ab_planes3d(pairs, side):
    """Chained SpMV-only A/B: f32 planes vs bf16 planes, BOTH with f32
    vectors, on the 3D clustered kernel.  Isolates the mixed tier's
    3D loss (VERDICT item 5) to the kernel's bf16-plane path: the
    traffic model says bf16 planes should win ~1.3x; two rounds of
    ladders measured the opposite inside the full CG loop."""
    import functools

    import numpy as np
    import jax
    import jax.numpy as jnp

    from acg_tpu._platform import device_sync
    from acg_tpu.io.generators import poisson_dia_device
    from acg_tpu.ops.pallas_kernels import dia_spmv

    chains = {}
    for name, dt in (("f32", jnp.float32), ("bf16", jnp.bfloat16)):
        planes, offsets, N = poisson_dia_device(side, 3, dtype=dt)

        @functools.partial(jax.jit, static_argnames=("k", "offs"))
        def prog(planes, x, k, offs):
            def body(_, v):
                y = dia_spmv(planes, offs, v)
                return y / jnp.linalg.norm(y)

            return jax.lax.fori_loop(0, k, body, x)

        x0 = jnp.ones(N, jnp.float32)
        chains[name] = (prog, tuple(planes), x0, offsets)

    k_long = 60 if side >= 512 else 200

    def rate(name):
        prog, planes, x0, offs = chains[name]
        return _chained_rate(
            lambda k: device_sync(prog(planes, x0, k, offs)), k_long)

    _emit_interleaved(f"bf16planes_vs_f32planes_spmv_3d{side}",
                      lambda: rate("bf16"), lambda: rate("f32"),
                      "bf16_planes", "f32_planes", pairs,
                      extra={"side": side})


def ab_bell(pairs):
    """Chained-SpMV throughput of the two stacked local-block layouts on
    the 500k power-law workload (the SpMV is where the layouts differ;
    whole-CG dist solves would fold in the unrelated dist-program
    overhead under diagnosis as `dist1`).  Normalising each application
    keeps the chain data-dependent without overflow."""
    import functools

    import numpy as np
    import jax
    import jax.numpy as jnp

    from acg_tpu._platform import device_sync
    from acg_tpu.io.generators import irregular_spd_coo
    from acg_tpu.matrix import SymCsrMatrix
    from acg_tpu.parallel.dist import DistributedProblem, _stack_local_blocks
    from acg_tpu.partition import partition_rows

    r, c, v, N = irregular_spd_coo(500_000, avg_degree=16.0, seed=0)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    part = partition_rows(csr, 1, seed=0, method="graph")
    prob = DistributedProblem.build(csr, part, 1, dtype=jnp.float32)
    assert prob.local.format == "binnedell", prob.local.format
    ell = _stack_local_blocks(prob.subs, prob.nmax_owned, jnp.float32,
                              ell_waste_limit=1e30)
    assert ell.format == "ell"

    def chained(block):
        arrays0 = jax.tree.map(lambda a: jnp.asarray(a[0]), block.arrays)

        @functools.partial(jax.jit, static_argnames="k")
        def prog(arrays, x, k):
            def body(_, v):
                y = block.shard_mv(arrays, v)
                return y / jnp.linalg.norm(y)

            return jax.lax.fori_loop(0, k, body, x)

        x0 = jnp.ones(prob.nmax_owned, jnp.float32)
        return lambda: _chained_rate(
            lambda k: device_sync(prog(arrays0, x0, k)), 200)

    _emit_interleaved("dist_bell_vs_ell_spmv_irregular500k",
                      chained(prob.local), chained(ell),
                      "binnedell", "ell", pairs,
                      extra={"ell_K": int(np.diff(csr.indptr).max())})


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", default=None,
                    help="comma list of: dist1,mixed3d,bell,roll3d,"
                         "proll,planes3d")
    ap.add_argument("--pairs", type=int, default=4)
    ap.add_argument("--big", action="store_true",
                    help="mixed3d at 512^3 instead of 256^3")
    args = ap.parse_args(argv)
    only = set(args.only.split(",")) if args.only else None

    from acg_tpu._platform import enable_compile_cache
    enable_compile_cache()
    from bench import bandwidth_probe_gbs
    try:
        print(f"# probe: {bandwidth_probe_gbs(refresh=True):.0f} GB/s",
              file=sys.stderr)
    except Exception as e:
        print(f"# probe failed: {e}", file=sys.stderr)

    for key, fn in (("dist1", lambda: ab_dist1(args.pairs)),
                    ("bell", lambda: ab_bell(args.pairs)),
                    ("mixed3d", lambda: ab_mixed3d(
                        args.pairs, 512 if args.big else 256)),
                    ("roll3d", lambda: ab_roll3d(
                        args.pairs, 512 if args.big else 256)),
                    ("proll", lambda: ab_proll(
                        args.pairs, 512 if args.big else 256)),
                    ("planes3d", lambda: ab_planes3d(
                        args.pairs, 512 if args.big else 256))):
        if only is not None and key not in only:
            continue
        try:
            fn()
        except Exception as e:  # noqa: BLE001 -- keep the rest of the set
            print(f"# {key} failed: {type(e).__name__}: "
                  f"{str(e).splitlines()[0][:200]}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
