#!/bin/bash
# Full BASELINE ladder as PER-ROW driver invocations: one process per
# row, each appending its JSON line to the output file as it lands --
# a contention burst, watchdog kill, or tunnel drop takes out at most
# the row it hits instead of the rest of the ladder (round-3 verdict
# item 8; the reference's sweep protocol similarly runs one mpiexec
# per configuration, scripts/nccl_combined.sh:48-176).
#
# Usage: scripts/ladder.sh [OUTPUT.jsonl]
set -u
cd "$(dirname "$0")/.."
OUT=${1:-LADDER.jsonl}

ROWS=(
  cg_iters_per_sec_poisson2d_n2048_f32
  cg_xla_iters_per_sec_poisson2d_n2048_f32
  cg_iters_per_sec_poisson2d_n2048_mixed
  cg_iters_per_sec_poisson2d_n2048_bf16
  cg_iters_per_sec_poisson2d_n2048_bf16rr
  cg_pipelined_iters_per_sec_poisson2d_n2048_f32
  cg_iters_per_sec_poisson3d_n128_f32
  cg_pipelined_iters_per_sec_poisson3d_n128_f32
  cg_iters_per_sec_poisson3d_n256_f32
  cg_iters_per_sec_poisson3d_n256_mixed
  cg_dist1_iters_per_sec_poisson2d_n2048_f32
  cg_iters_per_sec_irregular_n500k_d16_f32
  cg_coo_iters_per_sec_irregular_n500k_d16_f32
  cg_iters_per_sec_poisson3d_n128_petsc_f64
  cg_iters_per_sec_poisson3d_n128_hostnative_f64
  cg_iters_per_sec_poisson3d_n512_f32_dia
  cg_iters_per_sec_poisson3d_n512_mixed_dia
  cg_iters_per_sec_poisson3d_n512_bf16rr_dia
  cg_iters_per_sec_poisson3d_n256_bf16rr_dia
)

for row in "${ROWS[@]}"; do
  echo "# ladder row: $row" >&2
  timeout 900 python bench.py --full --row "$row" >> "$OUT"
  rc=$?
  if [ $rc -ne 0 ]; then
    echo "{\"metric\": \"$row\", \"skipped\": true, \"rc\": $rc}" >> "$OUT"
  fi
done
echo "# ladder complete: $(grep -c '"metric"' "$OUT") rows in $OUT" >&2
