#!/usr/bin/env python3
"""Validate a ``--timeline`` Chrome trace-event file.

The CI trace-smoke step (and any pipeline consuming ``--timeline``
output) needs a mechanical check that the exporter keeps its contract:

* the document is valid JSON with a ``traceEvents`` list (the Chrome
  trace-event JSON-object form Perfetto loads);
* every complete (``X``) event carries a name and numeric ``ts``/``dur``
  with ``dur >= 0``;
* ``ts`` is monotone non-decreasing per (pid, tid) track -- the
  exporter sorts, so a violation means a torn write or a foreign tool;
* every pid that carries span events has ``process_name`` metadata;
* with ``--parts N``: span events cover exactly N distinct pids (the
  one-pid-per-part contract of acg_tpu.tracing.export_chrome_trace);
* with ``--require-span NAME`` (repeatable): at least one ``X`` event
  with exactly that name exists;
* cross-rank clock alignment left no negative skew: the metadata's
  ``clock.max_skew_s`` is recorded and, when alignment ran, every
  rank's spans start at or after the timeline origin (ts >= 0).

Exit status: 0 valid, 1 invalid (each failure is printed), 2 usage.
Stdlib-only on purpose -- runs on a bare pod VM with no repo install.
"""

from __future__ import annotations

import argparse
import json
import math
import sys


def validate(doc, parts=None, require_spans=()) -> list[str]:
    """All contract violations in ``doc`` (empty = valid)."""
    errs: list[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object (the Chrome trace-event "
                "JSON-array form carries no metadata; the exporter "
                "always writes the object form)"]
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["no traceEvents list"]

    named_pids: set[int] = set()
    span_pids: set[int] = set()
    span_names: set[str] = set()
    tracks: dict[tuple, float] = {}
    for i, e in enumerate(events):
        if not isinstance(e, dict) or "ph" not in e:
            errs.append(f"event {i}: not an event object (no ph)")
            continue
        ph = e["ph"]
        if ph == "M":
            if e.get("name") == "process_name":
                named_pids.add(e.get("pid"))
            continue
        if ph not in ("X", "i", "I"):
            continue
        name = e.get("name")
        ts = e.get("ts")
        if not name or not isinstance(name, str):
            errs.append(f"event {i}: {ph} event without a name")
            continue
        if not isinstance(ts, (int, float)) or not math.isfinite(ts):
            errs.append(f"event {i} ({name}): non-numeric ts {ts!r}")
            continue
        if ts < 0:
            errs.append(f"event {i} ({name}): negative ts {ts} -- a "
                        f"span precedes the aligned timeline origin "
                        f"(negative inter-rank skew)")
        track = (e.get("pid"), e.get("tid"))
        last = tracks.get(track)
        if last is not None and ts < last:
            errs.append(f"event {i} ({name}): ts {ts} < previous "
                        f"{last} on track pid={track[0]} "
                        f"tid={track[1]} (non-monotone)")
        tracks[track] = ts
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) \
                    or not math.isfinite(dur) or dur < 0:
                errs.append(f"event {i} ({name}): bad dur {dur!r}")
            span_pids.add(e.get("pid"))
            span_names.add(name)

    unnamed = span_pids - named_pids
    if unnamed:
        errs.append(f"pids without process_name metadata: "
                    f"{sorted(unnamed)}")
    if parts is not None and len(span_pids) != parts:
        errs.append(f"expected spans on exactly {parts} pids (one per "
                    f"part), found {len(span_pids)}: "
                    f"{sorted(span_pids)}")
    for want in require_spans:
        if want not in span_names:
            errs.append(f"required span {want!r} absent (have: "
                        f"{', '.join(sorted(span_names)) or 'none'})")
    return errs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate a --timeline Chrome trace-event file")
    ap.add_argument("file", help="timeline JSON file")
    ap.add_argument("--parts", type=int, default=None, metavar="N",
                    help="require spans on exactly N pids (one per "
                         "part)")
    ap.add_argument("--require-span", action="append", default=[],
                    metavar="NAME",
                    help="require a span with this exact name "
                         "(repeatable)")
    args = ap.parse_args(argv)

    try:
        with open(args.file) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"check_timeline: {args.file}: {e}", file=sys.stderr)
        return 1
    errs = validate(doc, parts=args.parts,
                    require_spans=args.require_span)
    if errs:
        for e in errs:
            print(f"check_timeline: {args.file}: {e}", file=sys.stderr)
        return 1
    nspans = sum(1 for e in doc["traceEvents"]
                 if isinstance(e, dict) and e.get("ph") == "X")
    pids = {e.get("pid") for e in doc["traceEvents"]
            if isinstance(e, dict) and e.get("ph") == "X"}
    md = doc.get("metadata", {})
    clock = md.get("clock", {})
    print(f"check_timeline: {args.file}: OK ({nspans} spans over "
          f"{len(pids)} pid(s), {md.get('nranks', 1)} rank(s), "
          f"max skew {clock.get('max_skew_s', 0.0):.6f} s)")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # stdout consumer (head, grep -m) closed early -- the cli.py
        # SIGPIPE recipe: point the fd at devnull so the interpreter's
        # exit flush cannot print a traceback after a clean verdict
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
