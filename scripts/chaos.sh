#!/usr/bin/env bash
# Chaos campaign driver: seeded randomized fault schedules through the
# survivor-mesh supervisor on the 8-part CPU mesh (the elastic-recovery
# acceptance, ISSUE 10).  Every schedule must end converged or
# agreed-abort; a single wrong-answer-green run fails the campaign
# (exit 96, errors.ExitCode.WRONG_ANSWER).
#
# Usage: scripts/chaos.sh [SEED[:N]] [extra acg-tpu flags...]
#   SEED[:N]   campaign seed and schedule count (default 1234:20)
#
# Environment:
#   CHAOS_MATRIX   matrix spec (default gen:poisson2d:20)
#   CHAOS_NPARTS   mesh size (default 8; 0 = single device)
#   CHAOS_DIR      scratch/ledger directory (default a mktemp dir)
#
# The campaign arms --abft --audit-every (so sdc:flip schedules are
# detectable), snapshots every 8 iterations (so crash:exit schedules
# are resumable), and records per-schedule verdicts into the
# $CHAOS_DIR/history ledger plus the acg_recovery_* metric families in
# $CHAOS_DIR/chaos.prom.
set -o pipefail
cd "$(dirname "$0")/.."

SPEC="${1:-1234:20}"
shift 2>/dev/null || true
MATRIX="${CHAOS_MATRIX:-gen:poisson2d:20}"
NPARTS="${CHAOS_NPARTS:-8}"
DIR="${CHAOS_DIR:-$(mktemp -d /tmp/acg-chaos.XXXXXX)}"
mkdir -p "$DIR"

PARTS_FLAGS=()
ENV_FLAGS=(JAX_PLATFORMS=cpu)
if [ "$NPARTS" -gt 1 ]; then
    PARTS_FLAGS=(--nparts "$NPARTS" --shrink any)
    ENV_FLAGS+=("XLA_FLAGS=--xla_force_host_platform_device_count=$NPARTS")
else
    PARTS_FLAGS=(--comm none)
fi

echo "chaos.sh: campaign $SPEC on $MATRIX ($NPARTS parts) -> $DIR"
env "${ENV_FLAGS[@]}" python -m acg_tpu.cli "$MATRIX" \
    "${PARTS_FLAGS[@]}" \
    --max-iterations 400 --residual-rtol 1e-8 --warmup 0 --quiet \
    --ckpt "$DIR/ck" --ckpt-every 8 \
    --audit-every 5 --abft \
    --chaos "$SPEC" --relaunch-backoff 0 \
    --history "$DIR/history" \
    --metrics-file "$DIR/chaos.prom" \
    "$@"
rc=$?
if [ $rc -eq 96 ]; then
    echo "chaos.sh: WRONG-ANSWER-GREEN detected (exit 96) -- see $DIR"
elif [ $rc -ne 0 ]; then
    echo "chaos.sh: campaign driver failed (exit $rc)"
else
    echo "chaos.sh: campaign clean (ledger: $DIR/history)"
fi
exit $rc
