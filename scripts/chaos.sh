#!/usr/bin/env bash
# Chaos campaign driver: seeded randomized fault schedules through the
# survivor-mesh supervisor on the 8-part CPU mesh (the elastic-recovery
# acceptance, ISSUE 10).  Every schedule must end converged or
# agreed-abort; a single wrong-answer-green run fails the campaign
# (exit 96, errors.ExitCode.WRONG_ANSWER).
#
# Usage: scripts/chaos.sh [--serve] [SEED[:N]] [extra acg-tpu flags...]
#   --serve    run the campaign against a LIVE --serve daemon instead
#              of one solve per schedule: a supervised solver service
#              is launched, N seeded request-level schedules (crashes
#              mid-request, slow-solve stalls, device fault injections)
#              are fired at it over HTTP, and every response is
#              verified against a host-side oracle.  A wrong answer
#              under a green status exits 96; a hung request (neither
#              answer nor typed refusal) exits 1.
#   SEED[:N]   campaign seed and schedule count (default 1234:20)
#
# Environment:
#   CHAOS_MATRIX   matrix spec (default gen:poisson2d:20)
#   CHAOS_NPARTS   mesh size (default 8; 0 = single device;
#                  --serve mode defaults to 0)
#   CHAOS_DIR      scratch/ledger directory (default a mktemp dir)
#
# The solve-per-schedule campaign arms --abft --audit-every (so
# sdc:flip schedules are detectable), snapshots every 8 iterations (so
# crash:exit schedules are resumable), and records per-schedule
# verdicts into the $CHAOS_DIR/history ledger plus the acg_recovery_*
# metric families in $CHAOS_DIR/chaos.prom.  The --serve campaign
# records one acg-tpu-chaos-serve/1 verdict row per request instead.
set -o pipefail
cd "$(dirname "$0")/.."

SERVE=0
if [ "${1:-}" = "--serve" ]; then
    SERVE=1
    shift
fi

SPEC="${1:-1234:20}"
shift 2>/dev/null || true
MATRIX="${CHAOS_MATRIX:-gen:poisson2d:20}"
if [ "$SERVE" = "1" ]; then
    NPARTS="${CHAOS_NPARTS:-0}"
else
    NPARTS="${CHAOS_NPARTS:-8}"
fi
DIR="${CHAOS_DIR:-$(mktemp -d /tmp/acg-chaos.XXXXXX)}"
mkdir -p "$DIR"

PARTS_FLAGS=()
ENV_FLAGS=(JAX_PLATFORMS=cpu)
if [ "$NPARTS" -gt 1 ]; then
    PARTS_FLAGS=(--nparts "$NPARTS" --shrink any)
    ENV_FLAGS+=("XLA_FLAGS=--xla_force_host_platform_device_count=$NPARTS")
else
    PARTS_FLAGS=(--comm none)
fi

if [ "$SERVE" = "1" ]; then
    echo "chaos.sh: SERVE campaign $SPEC on $MATRIX ($NPARTS parts) -> $DIR"
    env "${ENV_FLAGS[@]}" python -m acg_tpu.cli "$MATRIX" \
        "${PARTS_FLAGS[@]}" \
        --serve --serve-faults \
        --max-iterations 400 --residual-rtol 1e-8 --quiet \
        --ckpt "$DIR/ck" \
        --chaos "$SPEC" --relaunch-backoff 0 \
        --history "$DIR/history" \
        --metrics-file "$DIR/chaos.prom" \
        "$@"
    rc=$?
    if [ $rc -eq 96 ]; then
        echo "chaos.sh: WRONG-ANSWER-GREEN detected (exit 96) -- see $DIR"
    elif [ $rc -ne 0 ]; then
        echo "chaos.sh: serve campaign failed (exit $rc) -- see $DIR"
    else
        echo "chaos.sh: serve campaign clean (ledger: $DIR/history)"
    fi
    exit $rc
fi

echo "chaos.sh: campaign $SPEC on $MATRIX ($NPARTS parts) -> $DIR"
env "${ENV_FLAGS[@]}" python -m acg_tpu.cli "$MATRIX" \
    "${PARTS_FLAGS[@]}" \
    --max-iterations 400 --residual-rtol 1e-8 --warmup 0 --quiet \
    --ckpt "$DIR/ck" --ckpt-every 8 \
    --audit-every 5 --abft \
    --chaos "$SPEC" --relaunch-backoff 0 \
    --history "$DIR/history" \
    --metrics-file "$DIR/chaos.prom" \
    "$@"
rc=$?
if [ $rc -eq 96 ]; then
    echo "chaos.sh: WRONG-ANSWER-GREEN detected (exit 96) -- see $DIR"
elif [ $rc -ne 0 ]; then
    echo "chaos.sh: campaign driver failed (exit $rc)"
else
    echo "chaos.sh: campaign clean (ledger: $DIR/history)"
fi
exit $rc
