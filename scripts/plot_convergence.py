#!/usr/bin/env python3
"""Render convergence logs and service-metrics captures side by side.

Accepts any mix of:

* ``--convergence-log`` JSONL files (residual history per iteration);
* ``--stats-json`` documents, schema ``acg-tpu-stats/3`` (a soak run's
  latency/iteration percentiles, and the embedded registry snapshot's
  latency histogram when the metrics layer was armed);
* ``--metrics-file`` Prometheus textfiles (the ``acg_solve_seconds``
  histogram and its percentiles re-derived from the bucket counts);
* ``--history`` run-ledger JSONL partitions (acg-tpu-history/1 index
  lines): a latency-over-time trend panel, one line per case, renders
  next to the residual plot (ascii: per-case latency sparklines);
* ``--access-log`` request ledgers (acg-tpu-access/1, the solver
  service's one-row-per-request attribution): a per-stage STACKED
  latency panel (one bar per request, ledger order) plus the outcome
  histogram (ascii: stage p50/p95 lines and outcome bars).

With matplotlib: a semilog residual plot (one line per log, wrap
markers where a ring truncated) and, when any latency input is given,
a latency-histogram bar panel beside it; written to ``-o OUT.png`` or
shown.  Without matplotlib (or under ``--ascii``): unicode sparklines
-- log-scaled blocks for residuals, linear blocks over the occupied
latency buckets -- plus a p50/p95/p99 summary line, so the tool works
on a bare pod VM.

Usage:
    python scripts/plot_convergence.py run1.jsonl [soak.prom s.json ...] \
        [-o compare.png] [--ascii]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BLOCKS = "▁▂▃▄▅▆▇█"


def _load_conv(path):
    from acg_tpu.telemetry import read_convergence_log

    meta, records = read_convergence_log(path)
    its = [r["it"] for r in records]
    # batched multi-RHS logs (/9): rnrm2 is a per-RHS COLUMN -- keep
    # the full fan for the matplotlib renderer (thin line per RHS,
    # worst highlighted) and collapse to the worst RHS for the scalar
    # consumers (the ascii sparkline's documented fallback)
    if int(meta.get("nrhs") or 0) > 1 or any(
            isinstance(r.get("rnrm2"), list) for r in records):
        fan = [[float(v) for v in r["rnrm2"]] for r in records]
        meta["_fan"] = fan
        rn = [float(r["worst"]) if "worst" in r
              else max((v for v in row if math.isfinite(v)),
                       default=math.nan)
              for r, row in zip(records, fan)]
        return meta, its, rn, None
    # poisoned values arrive as repr strings ("nan"/"inf"); float()
    # parses those directly, so they stay non-finite for the renderers
    rn = [float(r["rnrm2"]) for r in records]
    # the numerical-health tier's audit column (/5): present when the
    # meta "fields" list declares it; NaN on unaudited iterations, so
    # mixed windows align by construction
    gaps = None
    if any("gap" in r for r in records):
        gaps = [float(r["gap"]) if "gap" in r else math.nan
                for r in records]
    return meta, its, rn, gaps


# -- latency inputs ------------------------------------------------------

def _hist_quantile(cum, q: float):
    """``histogram_quantile`` over ``[(upper_bound, cumulative), ...]``
    ending with the +Inf bucket -- the same estimator acg_tpu.metrics
    uses, re-implemented here so the script stays runnable against a
    bare textfile with no package import needed at render time."""
    total = cum[-1][1]
    if total <= 0:
        return None
    rank = q * total
    prev_ub, prev_c = 0.0, 0
    for ub, c in cum:
        if c >= rank:
            if math.isinf(ub):
                return prev_ub or None
            if c == prev_c:
                return ub
            return prev_ub + (ub - prev_ub) * (rank - prev_c) / (c - prev_c)
        prev_ub, prev_c = ub, c
    return prev_ub


_BUCKET_RE = re.compile(
    r'^acg_solve_seconds_bucket\{[^}]*le="([^"]+)"[^}]*\}\s+(\S+)$')


def _load_metrics_textfile(path):
    """The ``acg_solve_seconds`` histogram out of a Prometheus
    textfile: ``(cumulative_buckets, count)``."""
    buckets: dict[float, int] = {}
    count = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            m = _BUCKET_RE.match(line)
            if m:
                ub = math.inf if m.group(1) == "+Inf" else float(m.group(1))
                buckets[ub] = buckets.get(ub, 0) + int(float(m.group(2)))
            elif line.startswith("acg_solve_seconds_count"):
                count += int(float(line.rsplit(None, 1)[1]))
    if not buckets:
        raise ValueError("no acg_solve_seconds histogram in textfile "
                         "(not a --metrics-file capture?)")
    cum = sorted(buckets.items())
    if not math.isinf(cum[-1][0]):
        cum.append((math.inf, count or cum[-1][1]))
    return cum, count or cum[-1][1]


def _load_stats_json(path):
    """Latency + health evidence out of an ``acg-tpu-stats`` document
    (single document or the first JSONL line): the soak report's
    percentiles, the registry snapshot's latency buckets, and the /5
    ``health`` section (audit gap summary + Lanczos spectrum) when
    present."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
        for line in text.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                doc = json.loads(line)
                break
            except ValueError:
                continue
    if not isinstance(doc, dict) or "stats" not in doc:
        raise ValueError("not an acg-tpu-stats document")
    soak = (doc.get("stats") or {}).get("soak") or {}
    health = (doc.get("stats") or {}).get("health") or {}
    cum = None
    samples = ((doc.get("metrics") or {}).get("acg_solve_seconds")
               or {}).get("samples") or []
    if samples:
        cum = [((math.inf if ub is None else float(ub)), int(c))
               for ub, c in samples[0].get("buckets", [])]
    # survivability evidence (/6): rollback / resume / restart events
    # with their iteration numbers, for the residual-trail markers
    events = []
    for ev in (doc.get("stats") or {}).get("events") or []:
        kind = ev.get("kind")
        if kind not in ("rollback", "resume", "restart", "breakdown"):
            continue
        m = re.search(r"iteration (\d+)", str(ev.get("detail", "")))
        if m:
            events.append((kind, int(m.group(1))))
    return soak, cum, health, events


def _latency_summary(label, soak, cum, health=None, events=None):
    """One record the renderers share: percentiles (soak report first,
    histogram-derived otherwise) + the occupied bucket histogram + the
    /5 health annotation (audit gap, kappa estimate, predicted-vs-
    measured iterations)."""
    pcts = {}
    lat = soak.get("latency") or {}
    for k in ("p50", "p95", "p99"):
        if lat.get(k) is not None:
            pcts[k] = float(lat[k])
    if not pcts and cum is not None:
        for q, k in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            v = _hist_quantile(cum, q)
            if v is not None:
                pcts[k] = v
    return {"label": label, "pcts": pcts, "cum": cum,
            "nsolves": soak.get("nsolves"),
            "drift": soak.get("drift") or {},
            "health": health or {},
            "events": events or []}


def _health_note(health) -> str | None:
    """The one-line kappa / audit annotation for a /5 health section
    (shared by the text fallback and the matplotlib title)."""
    if not health:
        return None
    bits = []
    if health.get("gap_max") is not None:
        bits.append(f"audit gap max {health['gap_max']:.3g}"
                    + (f" (x{health['naudits']} audits)"
                       if health.get("naudits") else ""))
    spec = health.get("spectrum") or {}
    if spec.get("kappa"):
        bits.append(f"kappa~{spec['kappa']:.4g}")
    if spec.get("predicted_iterations"):
        bits.append(f"CG bound {spec['predicted_iterations']} its vs "
                    f"measured {spec.get('measured_iterations', '?')}")
    if spec.get("precond_effectiveness"):
        bits.append(f"precond {spec['precond_effectiveness']:.2f}x")
    return "; ".join(bits) if bits else None


def _fmt_s(v: float) -> str:
    return f"{v * 1e3:.3g} ms" if v < 1.0 else f"{v:.3g} s"


def _occupied(cum):
    """Non-cumulative counts over the occupied finite-bucket window:
    ``(edges, counts)``."""
    counts, edges, prev = [], [], 0
    for ub, c in cum:
        counts.append(c - prev)
        edges.append(ub)
        prev = c
    nz = [i for i, c in enumerate(counts) if c > 0]
    if not nz:
        return [], []
    lo, hi = nz[0], nz[-1]
    return edges[lo:hi + 1], counts[lo:hi + 1]


def _latency_text(rec) -> list[str]:
    head = rec["label"]
    if rec["nsolves"]:
        head += f" [{rec['nsolves']} solves]"
    p = rec["pcts"]
    if p:
        head += ("  latency "
                 + "  ".join(f"{k} {_fmt_s(v)}"
                             for k, v in sorted(p.items())))
    drift = rec["drift"]
    if drift.get("ratio") is not None:
        head += f"  drift x{drift['ratio']:.2f}"
        if drift.get("tripped"):
            head += " (TRIPPED)"
    lines = [head]
    note = _health_note(rec.get("health"))
    if note:
        lines.append(f"  health: {note}")
    if rec["cum"]:
        edges, counts = _occupied(rec["cum"])
        if counts and all(math.isinf(e) for e in edges):
            lines.append(f"  ({counts[-1]} observation(s) past the "
                         f"bucket ladder)")
        elif counts:
            peak = max(counts)
            bar = "".join(
                BLOCKS[min(int(c / peak * (len(BLOCKS) - 1) + 0.5),
                           len(BLOCKS) - 1)] if c else "▁"
                for c in counts)
            lo = edges[0] if not math.isinf(edges[0]) else 0.0
            hi = next((e for e in reversed(edges)
                       if not math.isinf(e)), lo)
            lines.append(f"  {bar}  buckets {_fmt_s(lo)} .. "
                         f"{_fmt_s(hi)}")
    return lines


def _sparkline(its, rn, width: int = 72) -> str:
    finite = [v for v in rn if math.isfinite(v) and v > 0]
    if not finite:
        return "(no finite residuals)"
    lo = math.log10(min(finite))
    hi = math.log10(max(finite))
    span = max(hi - lo, 1e-12)
    # downsample to the terminal width by taking each bucket's max
    # (drift spikes must survive the downsampling -- they are the point)
    n = len(rn)
    step = max(n / width, 1.0)
    out = []
    i = 0.0
    while int(i) < n:
        chunk = rn[int(i): max(int(i + step), int(i) + 1)]
        worst = max((v for v in chunk if math.isfinite(v) and v > 0),
                    default=None)
        if worst is None:
            out.append("!")  # non-finite bucket: the breakdown marker
        else:
            frac = (math.log10(worst) - lo) / span
            out.append(BLOCKS[min(int(frac * (len(BLOCKS) - 1) + 0.5),
                                  len(BLOCKS) - 1)])
        i += step
    return "".join(out)


def _load_history(path):
    """A ``--history`` run-ledger JSONL partition (or a concatenation
    of them) -> per-case ``(times, latencies, iterations)`` trails for
    the latency-over-time trend panel.  Sniffs by content: at least one
    parseable line must carry the ``acg-tpu-history`` ledger marker.
    Backend-unavailable captures are skipped (no latency evidence)."""
    cases: dict[str, dict] = {}
    nledger = 0
    with open(path) as f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            try:
                obj = json.loads(raw)
            except ValueError:
                continue
            if not (isinstance(obj, dict) and str(
                    obj.get("ledger", "")).startswith("acg-tpu-history")):
                continue
            nledger += 1
            lat = obj.get("latency_s")
            if not isinstance(lat, (int, float)) or not \
                    math.isfinite(lat) or lat <= 0:
                continue
            case = str(obj.get("case") or "(uncased)")
            rec = cases.setdefault(case, {"t": [], "lat": [], "it": []})
            rec["t"].append(float(obj.get("unix_time") or 0.0))
            rec["lat"].append(float(lat))
            it = obj.get("iterations")
            rec["it"].append(int(it) if isinstance(it, (int, float))
                             else None)
    if not nledger:
        raise ValueError("no acg-tpu-history ledger lines")
    for rec in cases.values():
        order = sorted(range(len(rec["t"])), key=rec["t"].__getitem__)
        for key in ("t", "lat", "it"):
            rec[key] = [rec[key][i] for i in order]
    return {"path": path, "cases": cases, "nledger": nledger}


def _history_lines(rec) -> list[str]:
    """Ascii trend fallback: one latency sparkline per case (linear
    blocks over run order -- the drift spike must pop visually)."""
    lines = [f"{rec['path']}: run-history ledger, {rec['nledger']} "
             f"entr{'y' if rec['nledger'] == 1 else 'ies'}, "
             f"{len(rec['cases'])} case(s)"]
    for case in sorted(rec["cases"]):
        c = rec["cases"][case]
        lats = c["lat"]
        if not lats:
            lines.append(f"  {case}: (no timed runs)")
            continue
        peak = max(lats)
        bar = "".join(
            BLOCKS[min(int(v / peak * (len(BLOCKS) - 1) + 0.5),
                       len(BLOCKS) - 1)] for v in lats)
        lines.append(f"  {case}: {bar}  latency first "
                     f"{_fmt_s(lats[0])}  last {_fmt_s(lats[-1])}  "
                     f"best {_fmt_s(min(lats))} ({len(lats)} runs)")
    return lines


# the request observatory's stage vocabulary, in service order (kept
# in sync with acg_tpu.reqtrace.STAGES; re-declared so the script
# stays runnable against a bare ledger with no package import)
_ACCESS_STAGES = ("admit", "queue-wait", "coalesce", "cache",
                  "compile", "solve", "demux", "respond")


def _pctl(vals, q: float):
    """Sample percentile by rank interpolation (the ledger carries raw
    per-request seconds, not histogram buckets)."""
    vals = sorted(v for v in vals if math.isfinite(v))
    if not vals:
        return None
    rank = q * (len(vals) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(vals) - 1)
    return vals[lo] + (vals[hi] - vals[lo]) * (rank - lo)


def _load_access(path):
    """An ``--access-log`` request ledger (acg-tpu-access/1) -> the
    per-request stage/outcome evidence.  Sniffs by content: at least
    one parseable line must carry the access schema marker."""
    rows = []
    with open(path) as f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            try:
                obj = json.loads(raw)
            except ValueError:
                continue
            if isinstance(obj, dict) and str(
                    obj.get("schema", "")).startswith("acg-tpu-access"):
                rows.append(obj)
    if not rows:
        raise ValueError("no acg-tpu-access ledger rows")
    outcomes: dict[str, int] = {}
    for r in rows:
        o = str(r.get("outcome"))
        outcomes[o] = outcomes.get(o, 0) + 1
    return {"path": path, "rows": rows, "outcomes": outcomes}


def _access_lines(rec) -> list:
    """Ascii fallback for a request ledger: outcome bars plus the
    per-stage p50/p95 attribution."""
    rows = rec["rows"]
    lines = [f"{rec['path']}: request ledger, {len(rows)} request(s)"]
    peak = max(rec["outcomes"].values())
    for outcome, count in sorted(rec["outcomes"].items()):
        bar = "#" * max(int(count / peak * 24 + 0.5), 1)
        lines.append(f"  {outcome:<18} {bar} {count}")
    for name in _ACCESS_STAGES:
        vals = [float(r["stages"][name]) for r in rows
                if isinstance(r.get("stages"), dict)
                and isinstance(r["stages"].get(name), (int, float))]
        if not vals:
            continue
        lines.append(f"  {name:<12} p50 {_fmt_s(_pctl(vals, 0.5))}  "
                     f"p95 {_fmt_s(_pctl(vals, 0.95))}  "
                     f"({len(vals)} obs)")
    walls = [float(r["wall_seconds"]) for r in rows
             if isinstance(r.get("wall_seconds"), (int, float))]
    if walls:
        lines.append(f"  {'wall':<12} p50 {_fmt_s(_pctl(walls, 0.5))}  "
                     f"p95 {_fmt_s(_pctl(walls, 0.95))}  "
                     f"({len(walls)} obs)")
    return lines


def _load_timeline(path):
    """A ``--timeline`` Chrome trace-event file (acg-tpu-timeline/1)
    -> one span-summary record: per-name earliest start / latest end /
    total seconds aggregated over pids (a controller-wide span is
    replicated per part; the Gantt shows each name once).  The parse +
    shape check is tracing.read_timeline -- ONE reader for the format,
    shared with trace_report.py."""
    from acg_tpu.tracing import read_timeline

    doc = read_timeline(path)
    md = doc.get("metadata", {})
    if not str(md.get("schema", "")).startswith("acg-tpu-timeline"):
        raise ValueError("not an acg-tpu --timeline document")
    by_name: dict = {}
    nspans = 0
    for e in doc["traceEvents"]:
        if not isinstance(e, dict) or e.get("ph") != "X":
            continue
        nspans += 1
        t0 = e.get("ts", 0.0) * 1e-6
        t1 = t0 + e.get("dur", 0.0) * 1e-6
        name = e.get("name", "?")
        row = by_name.setdefault(name, [t0, t1, 0.0, set()])
        row[0] = min(row[0], t0)
        row[1] = max(row[1], t1)
        row[3].add(e.get("pid"))
    for name, row in by_name.items():
        # total = the span's wall window (replicas overlap exactly on
        # a single controller; across ranks the window includes skew)
        row[2] = row[1] - row[0]
    rows = sorted(({"name": n, "t0": r[0], "t1": r[1], "total": r[2],
                    "npids": len(r[3])} for n, r in by_name.items()),
                  key=lambda r: (r["t0"], r["t1"]))
    return {"path": path, "rows": rows, "nspans": nspans,
            "nparts": md.get("nparts", 0), "nranks": md.get("nranks", 1),
            "skew": md.get("clock", {}).get("max_skew_s", 0.0)}


def _gantt_lines(rec, width: int = 56) -> list:
    """Ascii Gantt of a timeline record -- the bare-pod-VM fallback."""
    rows = rec["rows"]
    t_end = max((r["t1"] for r in rows), default=0.0)
    lines = [f"{rec['path']}: {rec['nspans']} spans, "
             f"{rec['nparts']} part(s), {rec['nranks']} rank(s), "
             f"{t_end:.3f} s"]
    if t_end <= 0:
        return lines
    label_w = min(max((len(r["name"]) for r in rows), default=4), 24)
    for r in rows:
        a = int(r["t0"] / t_end * width)
        b = max(int(r["t1"] / t_end * width), a + 1)
        bar = " " * a + "#" * (b - a) + " " * (width - b)
        lines.append(f"  {r['name'][:label_w]:<{label_w}} |{bar}| "
                     f"{r['t0']:.3f}-{r['t1']:.3f}s")
    return lines


def _classify(path):
    """``("conv" | "latency" | "timeline" | "history" | "access",
    rec)`` by content, not extension: a convergence log's first
    parseable line is
    the meta record, a stats document has a ``stats`` key, anything
    with an ``acg_solve_seconds`` series is a metrics textfile, and an
    ``acg-tpu-timeline`` trace-event document renders as a per-phase
    span Gantt.  A /5 stats document carrying only a ``health`` section
    still classifies (the kappa annotation is its evidence)."""
    try:
        return ("timeline", _load_timeline(path))
    except (ValueError, UnicodeDecodeError):
        pass
    try:
        # an --access-log request ledger: acg-tpu-access rows (before
        # the history sniff -- both are JSONL, only access rows carry
        # the schema marker)
        return ("access", _load_access(path))
    except (ValueError, UnicodeDecodeError):
        pass
    try:
        # a --history ledger partition: acg-tpu-history index lines
        # (must sniff before the stats-document attempt -- the full
        # stats document rides INSIDE each ledger line)
        return ("history", _load_history(path))
    except (ValueError, UnicodeDecodeError):
        pass
    try:
        soak, cum, health, events = _load_stats_json(path)
        if soak or cum or health or events:
            return ("latency",
                    _latency_summary(os.path.basename(path), soak, cum,
                                     health, events))
        raise ValueError("stats document without latency, health or "
                         "survivability evidence (no soak/metrics/"
                         "health/events section)")
    except ValueError:
        pass
    try:
        cum, _n = _load_metrics_textfile(path)
        return ("latency",
                _latency_summary(os.path.basename(path), {}, cum))
    except (ValueError, UnicodeDecodeError):
        pass
    meta, its, rn, gaps = _load_conv(path)
    return ("conv", (path, meta, its, rn, gaps))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="plot --convergence-log residual histories and "
                    "--metrics-file/--stats-json latency captures")
    ap.add_argument("logs", nargs="+", metavar="FILE",
                    help="convergence-log JSONL, acg-tpu-stats JSON, "
                         "or Prometheus metrics textfile(s)")
    ap.add_argument("-o", "--output", metavar="PNG", default=None,
                    help="write the plot to PNG instead of showing it")
    ap.add_argument("--ascii", action="store_true",
                    help="force the text fallback even when matplotlib "
                         "is installed")
    args = ap.parse_args(argv)

    conv, latency, timelines, histories, accesses = [], [], [], [], []
    for path in args.logs:
        try:
            kind, rec = _classify(path)
        except (OSError, ValueError, KeyError) as e:
            print(f"plot_convergence: {path}: {e}", file=sys.stderr)
            return 1
        if kind == "conv":
            conv.append(rec)
        elif kind == "timeline":
            timelines.append(rec)
        elif kind == "history":
            histories.append(rec)
        elif kind == "access":
            accesses.append(rec)
        else:
            latency.append(rec)

    plt = None
    if not args.ascii:
        try:
            import matplotlib
            matplotlib.use("Agg" if args.output else matplotlib.get_backend())
            import matplotlib.pyplot as plt_mod
            plt = plt_mod
        except Exception:  # noqa: BLE001 -- fall back to text
            plt = None

    if plt is None:
        for path, meta, its, rn, gaps in conv:
            finite = [v for v in rn if math.isfinite(v) and v > 0]
            label = meta.get("solver", "cg")
            head = (f"{path} [{label}] iterations "
                    f"{its[0] if its else 0}..{its[-1] if its else 0}")
            if meta.get("wrapped"):
                head += (f" (ring wrapped: iterations before "
                         f"{meta.get('truncated_before', its[0] if its else 0)}"
                         f" truncated)")
            if meta.get("truncated"):
                head += " (trailing line truncated mid-write)"
            if meta.get("_fan"):
                # batched log: the ascii fallback shows the WORST RHS
                # only (the fan needs a real plot; run without --ascii)
                head += (f" [residual fan: {len(meta['_fan'][0])} RHS, "
                         f"worst shown]")
            print(head)
            print("  " + _sparkline(its, rn))
            if finite:
                print(f"  rnrm2 max {max(finite):.3e}  final "
                      f"{rn[-1]:.3e}" if math.isfinite(rn[-1])
                      else f"  rnrm2 max {max(finite):.3e}  final "
                           f"{rn[-1]!r} (breakdown)")
            gfin = [g for g in (gaps or []) if math.isfinite(g)]
            if gfin:
                # the true-residual-gap trail (audited iterations only)
                print("  gap: "
                      + _sparkline(list(range(len(gfin))), gfin))
                print(f"  audit gap max {max(gfin):.3e}  last "
                      f"{gfin[-1]:.3e} ({len(gfin)} audits)")
        for rec in latency:
            for line in _latency_text(rec):
                print(line)
            evs = rec.get("events") or []
            if evs:
                # survivability evidence (/6): where the solve rolled
                # back / resumed / restarted
                print("  events: "
                      + ", ".join(f"{k}@{i}" for k, i in evs))
        for rec in timelines:
            # per-phase span summary of a --timeline file (/7)
            for line in _gantt_lines(rec):
                print(line)
        for rec in histories:
            # per-case latency-over-time trend of a --history ledger
            for line in _history_lines(rec):
                print(line)
        for rec in accesses:
            # per-stage attribution + outcomes of an --access-log
            for line in _access_lines(rec):
                print(line)
        return 0

    ncols = ((1 if conv else 0) + (1 if latency else 0)
             + (1 if timelines else 0) + (1 if histories else 0)
             + (2 if accesses else 0)) or 1
    fig, axes = plt.subplots(1, ncols,
                             figsize=(9 if ncols == 1 else 6.5 * ncols,
                                      5))
    axes = [axes] if ncols == 1 else list(axes)
    ax = axes[0] if conv else None
    for path, meta, its, rn, gaps in conv:
        label = os.path.basename(path)
        if meta.get("wrapped"):
            label += " (truncated)"
        fan = meta.get("_fan")
        if fan:
            # the residual FAN of a batched log: one thin line per
            # RHS, the worst-RHS envelope highlighted on top -- the
            # per-request view of a coalesced batch
            nrhs = len(fan[0])
            for j in range(nrhs):
                ax.semilogy(
                    its,
                    [row[j] if math.isfinite(row[j]) and row[j] > 0
                     else float("nan") for row in fan],
                    linewidth=0.6, alpha=0.45)
            ax.semilogy(its,
                        [v if math.isfinite(v) and v > 0
                         else float("nan") for v in rn],
                        label=f"{label} (worst of {nrhs} RHS)",
                        linewidth=1.6, color="black")
            continue
        ax.semilogy(its, [v if math.isfinite(v) and v > 0 else float("nan")
                          for v in rn], label=label, linewidth=1.2)
        if gaps is not None:
            # the true-residual-gap trail on the same log axis: one
            # marker per audited iteration, dashed between them --
            # the drift the pipelined recurrences accumulate
            pts = [(i, g) for i, g in zip(its, gaps)
                   if math.isfinite(g) and g > 0]
            if pts:
                ax.semilogy([p[0] for p in pts], [p[1] for p in pts],
                            "--o", markersize=4, linewidth=0.9,
                            alpha=0.8, label=f"{label}: audit gap")
        # mark non-finite records (breakdown evidence) on the x-axis
        bad = [i for i, v in zip(its, rn) if not math.isfinite(v)]
        if bad:
            ax.plot(bad, [ax.get_ylim()[0]] * len(bad), "rx",
                    markersize=8, label=f"{label}: non-finite")
    if conv:
        # rollback/resume/restart markers from a /6 stats document
        # given alongside the log (the gap-overlay pattern): vertical
        # guides at the event iterations on the residual trail, so a
        # recovered solve shows WHERE it rolled back / resumed
        ev_style = {"rollback": ("tab:red", ":"),
                    "resume": ("tab:green", "--"),
                    "restart": ("tab:orange", ":"),
                    "breakdown": ("tab:red", "-.")}
        seen_kinds = set()
        for rec in latency:
            for kind, it in rec.get("events", []):
                c, ls = ev_style[kind]
                ax.axvline(it, color=c, linestyle=ls, alpha=0.7,
                           linewidth=1.1,
                           label=(kind if kind not in seen_kinds
                                  else None))
                seen_kinds.add(kind)
        ax.set_xlabel("iteration")
        ax.set_ylabel("residual 2-norm / audit gap")
        ax.grid(True, which="both", alpha=0.3)
        ax.legend(fontsize=8)
        notes = [n for n in (_health_note(rec.get("health"))
                             for rec in latency) if n]
        if notes:
            # kappa / predicted-iterations annotation from a /5 stats
            # document given alongside the logs
            ax.set_title("; ".join(notes), fontsize=8)
    if latency:
        lax = axes[1 if conv else 0]
        plotted = False
        for rec in latency:
            if not rec["cum"]:
                continue
            edges, counts = _occupied(rec["cum"])
            finite = [e for e in edges if not math.isinf(e)]
            if not counts or not finite:
                continue  # only the +Inf bucket occupied: no finite
                # position to anchor a log-axis step at
            # step plot at the TRUE bucket edges on a log axis --
            # multiple inputs with disjoint latency ranges keep their
            # own positions (a shared integer axis would mislabel all
            # but the last); the +Inf bucket renders one synthetic
            # decade past the last finite edge so overflow stays
            # visible
            xs = [(e if not math.isinf(e) else finite[-1] * 10)
                  for e in edges]
            lax.step(xs, counts, where="pre", marker="o",
                     markersize=3, alpha=0.8, label=rec["label"])
            plotted = True
        if plotted:
            lax.set_xscale("log")
        summary = "; ".join(
            f"{rec['label']}: "
            + " ".join(f"{k}={_fmt_s(v)}"
                       for k, v in sorted(rec["pcts"].items()))
            for rec in latency if rec["pcts"])
        lax.set_xlabel("solve latency bucket")
        lax.set_ylabel("solves")
        if summary:
            lax.set_title(summary, fontsize=8)
        if plotted:
            lax.legend(fontsize=8)
    if timelines:
        # one Gantt panel (broken_barh per span name) for the first
        # timeline; additional files fall back to the ascii summary so
        # N files never explode the figure (the history panel, when
        # present, owns the LAST column)
        tax = axes[(1 if conv else 0) + (1 if latency else 0)]
        rec = timelines[0]
        rows = rec["rows"]
        for i, r in enumerate(rows):
            tax.broken_barh([(r["t0"], max(r["t1"] - r["t0"], 1e-9))],
                            (i - 0.4, 0.8), alpha=0.85)
        tax.set_yticks(range(len(rows)))
        tax.set_yticklabels([r["name"] for r in rows], fontsize=7)
        tax.invert_yaxis()
        tax.set_xlabel("seconds since timeline origin")
        tax.set_title(f"{os.path.basename(rec['path'])}: "
                      f"{rec['nparts']} part(s), {rec['nranks']} "
                      f"rank(s)", fontsize=8)
        for extra in timelines[1:]:
            for line in _gantt_lines(extra):
                print(line)
    if histories:
        # the latency-over-time trend panel (one line per case) for the
        # first ledger; additional files fall back to the ascii summary
        # so N files never explode the figure
        hax = axes[(1 if conv else 0) + (1 if latency else 0)
                   + (1 if timelines else 0)]
        rec = histories[0]
        import datetime
        for case in sorted(rec["cases"]):
            c = rec["cases"][case]
            if not c["lat"]:
                continue
            xs = [datetime.datetime.fromtimestamp(t) for t in c["t"]]
            hax.plot(xs, c["lat"], "-o", markersize=3, alpha=0.85,
                     label=case, linewidth=1.1)
        hax.set_yscale("log")
        hax.set_xlabel("capture time")
        hax.set_ylabel("solve latency (s)")
        hax.set_title(f"{os.path.basename(rec['path'])}: "
                      f"{rec['nledger']} runs", fontsize=8)
        hax.tick_params(axis="x", labelsize=6, rotation=30)
        hax.legend(fontsize=7)
        for extra in histories[1:]:
            for line in _history_lines(extra):
                print(line)
    if accesses:
        # the request observatory's pair: a stacked per-stage latency
        # bar per request (ledger order) + the outcome histogram, for
        # the first ledger; extra files fall back to the ascii summary
        base = ((1 if conv else 0) + (1 if latency else 0)
                + (1 if timelines else 0) + (1 if histories else 0))
        aax, oax = axes[base], axes[base + 1]
        rec = accesses[0]
        rows = [r for r in rec["rows"]
                if isinstance(r.get("stages"), dict)]
        idx = list(range(len(rows)))
        bottom = [0.0] * len(rows)
        for name in _ACCESS_STAGES:
            vals = [float(r["stages"].get(name) or 0.0) for r in rows]
            if not any(vals):
                continue
            aax.bar(idx, vals, bottom=bottom, width=0.92, label=name)
            bottom = [b + v for b, v in zip(bottom, vals)]
        aax.set_xlabel("request (ledger order)")
        aax.set_ylabel("seconds")
        aax.set_title(f"{os.path.basename(rec['path'])}: per-stage "
                      f"latency ({len(rows)} request(s))", fontsize=8)
        aax.legend(fontsize=7)
        outs = sorted(rec["outcomes"].items())
        oax.bar(range(len(outs)), [v for _k, v in outs],
                color="tab:gray")
        oax.set_xticks(range(len(outs)))
        oax.set_xticklabels([k for k, _v in outs], fontsize=7,
                            rotation=30, ha="right")
        oax.set_ylabel("requests")
        oax.set_title("outcomes", fontsize=8)
        for extra in accesses[1:]:
            for line in _access_lines(extra):
                print(line)
    fig.tight_layout()
    if args.output:
        fig.savefig(args.output, dpi=130)
        print(f"wrote {args.output}")
    else:
        plt.show()
    return 0


if __name__ == "__main__":
    sys.exit(main())
