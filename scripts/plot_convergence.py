#!/usr/bin/env python3
"""Render one or more ``--convergence-log`` JSONL files as a residual-
history comparison.

With matplotlib: a semilog residual plot (one line per file, wrap
markers where a ring truncated) written to ``-o OUT.png`` or shown.
Without matplotlib (or under ``--ascii``): a text sparkline per file --
log-scaled unicode blocks over the surviving window -- so the tool
works on a bare pod VM.

Usage:
    python scripts/plot_convergence.py run1.jsonl [run2.jsonl ...] \
        [-o compare.png] [--ascii]
"""

from __future__ import annotations

import argparse
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BLOCKS = "▁▂▃▄▅▆▇█"


def _load(path):
    from acg_tpu.telemetry import read_convergence_log

    meta, records = read_convergence_log(path)
    its = [r["it"] for r in records]
    # poisoned values arrive as repr strings ("nan"/"inf"); float()
    # parses those directly, so they stay non-finite for the renderers
    rn = [float(r["rnrm2"]) for r in records]
    return meta, its, rn


def _sparkline(its, rn, width: int = 72) -> str:
    finite = [v for v in rn if math.isfinite(v) and v > 0]
    if not finite:
        return "(no finite residuals)"
    lo = math.log10(min(finite))
    hi = math.log10(max(finite))
    span = max(hi - lo, 1e-12)
    # downsample to the terminal width by taking each bucket's max
    # (drift spikes must survive the downsampling -- they are the point)
    n = len(rn)
    step = max(n / width, 1.0)
    out = []
    i = 0.0
    while int(i) < n:
        chunk = rn[int(i): max(int(i + step), int(i) + 1)]
        worst = max((v for v in chunk if math.isfinite(v) and v > 0),
                    default=None)
        if worst is None:
            out.append("!")  # non-finite bucket: the breakdown marker
        else:
            frac = (math.log10(worst) - lo) / span
            out.append(BLOCKS[min(int(frac * (len(BLOCKS) - 1) + 0.5),
                                  len(BLOCKS) - 1)])
        i += step
    return "".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="plot --convergence-log JSONL residual histories")
    ap.add_argument("logs", nargs="+", metavar="FILE",
                    help="convergence-log JSONL file(s)")
    ap.add_argument("-o", "--output", metavar="PNG", default=None,
                    help="write the plot to PNG instead of showing it")
    ap.add_argument("--ascii", action="store_true",
                    help="force the text sparkline fallback even when "
                         "matplotlib is installed")
    args = ap.parse_args(argv)

    loaded = []
    for path in args.logs:
        try:
            loaded.append((path,) + _load(path))
        except (OSError, ValueError, KeyError) as e:
            print(f"plot_convergence: {path}: {e}", file=sys.stderr)
            return 1

    plt = None
    if not args.ascii:
        try:
            import matplotlib
            matplotlib.use("Agg" if args.output else matplotlib.get_backend())
            import matplotlib.pyplot as plt_mod
            plt = plt_mod
        except Exception:  # noqa: BLE001 -- fall back to text
            plt = None

    if plt is None:
        for path, meta, its, rn in loaded:
            finite = [v for v in rn if math.isfinite(v) and v > 0]
            label = meta.get("solver", "cg")
            head = (f"{path} [{label}] iterations "
                    f"{its[0] if its else 0}..{its[-1] if its else 0}")
            if meta.get("wrapped"):
                head += (f" (ring wrapped: iterations before "
                         f"{meta.get('truncated_before', its[0] if its else 0)}"
                         f" truncated)")
            print(head)
            print("  " + _sparkline(its, rn))
            if finite:
                print(f"  rnrm2 max {max(finite):.3e}  final "
                      f"{rn[-1]:.3e}" if math.isfinite(rn[-1])
                      else f"  rnrm2 max {max(finite):.3e}  final "
                           f"{rn[-1]!r} (breakdown)")
        return 0

    fig, ax = plt.subplots(figsize=(9, 5))
    for path, meta, its, rn in loaded:
        label = os.path.basename(path)
        if meta.get("wrapped"):
            label += " (truncated)"
        ax.semilogy(its, [v if math.isfinite(v) and v > 0 else float("nan")
                          for v in rn], label=label, linewidth=1.2)
        # mark non-finite records (breakdown evidence) on the x-axis
        bad = [i for i, v in zip(its, rn) if not math.isfinite(v)]
        if bad:
            ax.plot(bad, [ax.get_ylim()[0]] * len(bad), "rx",
                    markersize=8, label=f"{label}: non-finite")
    ax.set_xlabel("iteration")
    ax.set_ylabel("residual 2-norm")
    ax.grid(True, which="both", alpha=0.3)
    ax.legend(fontsize=8)
    fig.tight_layout()
    if args.output:
        fig.savefig(args.output, dpi=130)
        print(f"wrote {args.output}")
    else:
        plt.show()
    return 0


if __name__ == "__main__":
    sys.exit(main())
