#!/usr/bin/env bash
# Solver x comm x nparts sweep -- the role of the reference's
# scripts/{mpi,nccl,nvshmem}_combined.sh (SURVEY.md component #28):
# run every solver variant over every transport at several mesh sizes on
# the same manufactured-solution Poisson problem and grep the
# "total solver time" line from each stats block.
#
# Usage: scripts/sweep.sh [N_SIDE] [MAXITS]
#   N_SIDE  side of the 2D Poisson grid (default 256 -> 65,536 unknowns;
#           the reference protocol uses 2048 -> 4.19M)
#   MAXITS  iteration cap (default 1000, reference protocol value)
#
# Without real multi-chip hardware the mesh sizes np>1 run on a virtual
# CPU device mesh (the analog of the reference's single-node np=1,2,4,8
# runs); on a TPU pod slice, drop the JAX_PLATFORMS/XLA_FLAGS overrides.

set -euo pipefail
cd "$(dirname "$0")/.."

N=${1:-256}
MAXITS=${2:-1000}
RTOL=1e-6
WORKDIR=$(mktemp -d)
trap 'rm -rf "$WORKDIR"' EXIT

export PYTHONPATH=${PYTHONPATH:-$PWD}
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
export XLA_FLAGS=${XLA_FLAGS:---xla_force_host_platform_device_count=8}

MTX="$WORKDIR/poisson2d_n$N.mtx"
echo "# generating 2D Poisson n=$N"
python -m acg_tpu.tools.genmatrix -n "$N" --dim 2 -o "$MTX"

for np in 1 2 4 8; do
    PART="$WORKDIR/part$np.mtx"
    python -m acg_tpu.tools.mtxpartition "$MTX" --parts "$np" > "$PART"
    for solver in acg acg-pipelined; do
        for comm in xla dma; do
            [ "$np" -eq 1 ] && [ "$comm" = dma ] && continue
            echo "=== solver=$solver comm=$comm np=$np ==="
            python -m acg_tpu.cli "$MTX" \
                --nparts "$np" --partition "$PART" \
                --solver "$solver" --comm "$comm" \
                --max-iterations "$MAXITS" --residual-rtol "$RTOL" \
                --manufactured-solution --warmup 1 --quiet 2>&1 |
                grep -E "total solver time|iterations:|error 2-norm" |
                sed 's/^/    /'
        done
    done
done
