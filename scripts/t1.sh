#!/usr/bin/env bash
# Tier-1 verify: the EXACT ROADMAP command, wrapped so CI and humans run
# the same thing (ROADMAP.md "Tier-1 verify").  Prints DOTS_PASSED (the
# per-test pass count the growth driver tracks) and exits with pytest's
# status.  Run from anywhere; executes at the repo root.
#
# T1_SOAK=1 additionally runs the service-soak smoke after the tests: a
# tiny 3-solve --soak run whose --metrics-file must validate as
# Prometheus exposition format and whose --stats-json must carry the
# acg-tpu-stats/12 soak section (the CI soak-smoke step runs the same
# thing).  T1_HEALTH=1 runs the numerical-health smoke: an audited
# pipelined solve on the anisotropic generator must leave a health:
# section with a finite gap, the acg_health_* metric families, and a
# Lanczos kappa estimate.  T1_CKPT=1 runs the crash/resume smoke: a
# soak solve is killed mid-flight by crash:exit@K, relaunched with
# --resume, and must converge with the acg_ckpt_* families exposed.
# T1_TRACE=1 runs the timeline-tracing smoke: an 8-part CPU-mesh solve
# under --trace/--timeline must leave a Chrome trace-event timeline
# that validates (scripts/check_timeline.py: one pid per part, spans
# for ingest/partition/compile/solve), a /7 stats document carrying
# the tracing: section, and the acg_trace_* metric families.
# T1_STATUS=1 runs the live-observatory smoke: a chunked 8-part
# CPU-mesh solve with --status-file + --history + --slo must leave a
# valid acg-tpu-status/1 document (solve converged, residual trail
# populated), one acg-tpu-history/1 ledger row that history_report.py
# renders, and the acg_slo_* metric families in the textfile.
# T1_CHAOS=1 runs the elastic-recovery smoke: crash:exit kills an
# 8-part checkpointed solve mid-flight, the supervisor relaunches it
# with --resume --resume-repartition on 4 parts (shrink), and the
# answer must verify against the host matrix; then a small seeded
# chaos campaign must end every schedule converged-or-agreed-abort
# (zero wrong-answer-green) with the acg_recovery_* families present.
# T1_BATCH=1 runs the batched multi-RHS smoke: an 8-part CPU-mesh
# solve of B=4 right-hand sides in ONE batched program must converge
# every column, leave a /9 stats document with the per-RHS batch:
# section, a status document whose solve.batch block names the
# slowest RHS, and one history ledger row carrying the batch section.
# T1_MATFREE=1 runs the matrix-free operator smoke: an 8-part mesh
# stencil solve under --operator stencil must converge with a printed
# solution BYTE-IDENTICAL to the assembled run's, carry the operator
# identity in the stats manifest, and declare matrix_free with a zero
# matrix-bytes term in the comm ledger.
# T1_COMMBENCH=1 runs the communication-observatory smoke: an 8-part
# --commbench sweep must emit a valid acg-tpu-commbench/1 document
# (fitted alpha-beta per collective kind, per-edge DMA rows, measured
# segments) and a calibrated --explain must print provenance with a
# predicted-vs-measured ratio strictly closer to 1.0 than the
# uncalibrated model's.
# T1_PLAN=1 runs the decision-observatory smoke: a --commbench sweep
# feeds an --autotune solve on the 8-part CPU mesh; the emitted
# acg-tpu-plan/1 document must validate with calibration provenance,
# the history ledger must carry the plan-vs-actual row (rendered by
# history_report.py's plan column), and the acg_plan_* metric
# families must land in the textfile.
# T1_SERVE=1 runs the solver-service smoke: a supervised 8-part
# --serve daemon answers two identical requests (the second must hit
# BOTH caches with acg_compiles_total unchanged -- zero ingest, zero
# compile), coalesces one compatible pair into a single batched solve,
# survives a crash-mid-request (supervisor relaunch + operator-cache
# warm restore on the same port), and shuts down clean on
# POST /shutdown (supervisor exit 0).
# T1_REQTRACE=1 runs the request-observatory smoke: an 8-part --serve
# daemon under --access-log + --timeline answers a burst that includes
# client/traceparent identities and one coalesced pair; the echoed
# request ids, the /requests ring, and the requests: status block must
# agree, the acg-tpu-access/1 ledger must validate
# (scripts/check_access_log.py) with the coalesced members sharing one
# batch block whose per-RHS attribution sums back to the batch solve
# time, access_report.py must render the p50/p95/p99 table and gate on
# --fail-on-p99 (exit 7), the exported SERVICE timeline must validate
# (scripts/check_timeline.py), and the exposition must carry
# acg_serve_stage_seconds / acg_serve_inflight.
set -o pipefail
cd "$(dirname "$0")/.."
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
if [ "${T1_SOAK:-0}" = "1" ]; then
    echo "T1_SOAK: 3-solve soak smoke"
    rm -f /tmp/_t1_soak.prom /tmp/_t1_soak.json
    timeout -k 10 300 env JAX_PLATFORMS=cpu python -m acg_tpu.cli \
        gen:poisson2d:16 --comm none --max-iterations 100 \
        --residual-rtol 1e-8 --warmup 0 --quiet --soak 3 \
        --metrics-file /tmp/_t1_soak.prom \
        --stats-json /tmp/_t1_soak.json || rc=$((rc ? rc : 1))
    python scripts/check_metrics_textfile.py /tmp/_t1_soak.prom \
        --require acg_solves_total --require acg_solve_seconds \
        --require acg_solve_iterations || rc=$((rc ? rc : 1))
    python - <<'PY' || rc=$((rc ? rc : 1))
import json
doc = json.load(open("/tmp/_t1_soak.json"))
assert doc["schema"] == "acg-tpu-stats/12", doc["schema"]
soak = doc["stats"]["soak"]
assert soak["nsolves"] == 3 and soak["latency"]["p50"] is not None, soak
assert "metrics" in doc, "registry snapshot missing from /3 document"
print("T1_SOAK: OK")
PY
fi
if [ "${T1_PRECOND:-0}" = "1" ]; then
    # preconditioning smoke (the PR-5 acceptance in miniature): jacobi
    # and cheby:4 PCG on the anisotropic generator must converge and
    # leave a /4 stats document carrying the precond section
    echo "T1_PRECOND: jacobi+cheby smoke"
    for pc in jacobi cheby:4; do
        rm -f /tmp/_t1_precond.json
        timeout -k 10 300 env JAX_PLATFORMS=cpu python -m acg_tpu.cli \
            gen:poisson2d:32 --aniso 0.05 --precond "$pc" --comm none \
            --max-iterations 500 --residual-rtol 1e-6 --warmup 0 \
            --quiet --stats-json /tmp/_t1_precond.json \
            || rc=$((rc ? rc : 1))
        env PC="$pc" python - <<'PY' || rc=$((rc ? rc : 1))
import json, os
doc = json.load(open("/tmp/_t1_precond.json"))
assert doc["schema"] == "acg-tpu-stats/12", doc["schema"]
st = doc["stats"]
assert st["converged"] is True, st["rnrm2"]
assert st["precond"]["kind"] == os.environ["PC"], st["precond"]
assert st["ops"]["precond"]["n"] > 0, st["ops"]["precond"]
print(f"T1_PRECOND: {os.environ['PC']} OK "
      f"({st['niterations']} iterations)")
PY
    done
fi
if [ "${T1_HEALTH:-0}" = "1" ]; then
    # numerical-health smoke (the PR-6 acceptance in miniature): an
    # audited f32 pipelined solve on the ill-conditioned aniso
    # generator -- whose recurrence residual drifts past the gap
    # threshold -- must RECOVER to the requested tolerance through
    # --on-gap replace (residual-replacement restarts), and leave a
    # health: section with a finite gap, the acg_health_* metric
    # families, and a Lanczos kappa estimate in the /5 stats document
    echo "T1_HEALTH: audit smoke"
    rm -f /tmp/_t1_health.json /tmp/_t1_health.prom /tmp/_t1_health.jsonl
    timeout -k 10 300 env JAX_PLATFORMS=cpu python -m acg_tpu.cli \
        gen:poisson2d:32 --aniso 0.05 --solver acg-pipelined \
        --dtype f32 --comm none --max-iterations 3000 \
        --residual-rtol 1e-5 --warmup 0 --quiet --audit-every 10 \
        --gap-threshold 1e-4 --on-gap replace --max-restarts 20 \
        --convergence-log /tmp/_t1_health.jsonl \
        --metrics-file /tmp/_t1_health.prom \
        --stats-json /tmp/_t1_health.json || rc=$((rc ? rc : 1))
    python scripts/check_metrics_textfile.py /tmp/_t1_health.prom \
        --require acg_health_residual_gap \
        --require acg_health_audits_total \
        --require acg_health_kappa_estimate \
        --require acg_health_gap_trips_total || rc=$((rc ? rc : 1))
    python - <<'PY' || rc=$((rc ? rc : 1))
import json, math
doc = json.load(open("/tmp/_t1_health.json"))
assert doc["schema"] == "acg-tpu-stats/12", doc["schema"]
h = doc["stats"]["health"]
assert h["naudits"] > 0, h
assert h["gap_last"] is not None and math.isfinite(h["gap_last"]), h
assert h["spectrum"]["kappa"] > 1, h["spectrum"]
print(f"T1_HEALTH: OK (gap {h['gap_last']:.3e}, "
      f"kappa {h['spectrum']['kappa']:.4g})")
PY
fi
if [ "${T1_CKPT:-0}" = "1" ]; then
    # crash/resume smoke (the PR-7 acceptance in miniature): a
    # checkpointed solve is hard-killed mid-flight by the crash:exit@K
    # fault (exit 94), relaunched with --resume from the committed
    # snapshot, and must reach the original tolerance; the metrics
    # textfile must expose the acg_ckpt_* family and the /6 stats
    # document the ckpt section with resume provenance
    echo "T1_CKPT: crash/resume smoke"
    rm -f /tmp/_t1_ckpt /tmp/_t1_ckpt.json /tmp/_t1_ckpt.prom
    timeout -k 10 300 env JAX_PLATFORMS=cpu python -m acg_tpu.cli \
        gen:poisson2d:24 --manufactured-solution --dtype f32 \
        --comm none --max-iterations 500 --residual-rtol 1e-5 \
        --warmup 0 --quiet --ckpt /tmp/_t1_ckpt --ckpt-every 8 \
        --fault-inject crash:exit@20
    crash_rc=$?
    if [ "$crash_rc" != "94" ]; then
        echo "T1_CKPT: expected crash exit 94, got $crash_rc"
        rc=$((rc ? rc : 1))
    fi
    timeout -k 10 300 env JAX_PLATFORMS=cpu python -m acg_tpu.cli \
        gen:poisson2d:24 --manufactured-solution --dtype f32 \
        --comm none --max-iterations 500 --residual-rtol 1e-5 \
        --warmup 0 --quiet --resume /tmp/_t1_ckpt \
        --metrics-file /tmp/_t1_ckpt.prom \
        --stats-json /tmp/_t1_ckpt.json || rc=$((rc ? rc : 1))
    python scripts/check_metrics_textfile.py /tmp/_t1_ckpt.prom \
        --require acg_ckpt_ || rc=$((rc ? rc : 1))
    python - <<'PY' || rc=$((rc ? rc : 1))
import json
doc = json.load(open("/tmp/_t1_ckpt.json"))
assert doc["schema"] == "acg-tpu-stats/12", doc["schema"]
st = doc["stats"]
assert st["converged"] is True, st["rnrm2"]
ck = st["ckpt"]
assert ck.get("resumed_from", 0) > 0, ck
print(f"T1_CKPT: OK (resumed at {ck['resumed_from']}, "
      f"+{st['niterations']} iterations to tolerance)")
PY
fi
if [ "${T1_TRACE:-0}" = "1" ]; then
    # timeline-tracing smoke (the PR-8 acceptance in miniature): an
    # 8-part CPU-mesh solve under --trace + --timeline must emit a
    # Chrome trace-event timeline with one pid per part and spans for
    # ingest/partition/compile/solve, a /7 stats document carrying the
    # tracing: section, and the acg_trace_* metric families; the
    # capture analysis must degrade gracefully on this CPU backend
    # (trace_report still exits 0 on the timeline)
    echo "T1_TRACE: 8-part timeline smoke"
    rm -rf /tmp/_t1_trace_capture
    rm -f /tmp/_t1_trace.json /tmp/_t1_timeline.json /tmp/_t1_trace.prom
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -m acg_tpu.cli gen:poisson2d:24 --nparts 8 \
        --max-iterations 200 --residual-rtol 1e-8 --warmup 1 --quiet \
        --trace /tmp/_t1_trace_capture \
        --timeline /tmp/_t1_timeline.json \
        --metrics-file /tmp/_t1_trace.prom \
        --stats-json /tmp/_t1_trace.json || rc=$((rc ? rc : 1))
    python scripts/check_timeline.py /tmp/_t1_timeline.json --parts 8 \
        --require-span ingest --require-span partition \
        --require-span compile --require-span solve \
        || rc=$((rc ? rc : 1))
    python scripts/trace_report.py /tmp/_t1_timeline.json \
        || rc=$((rc ? rc : 1))
    python scripts/check_metrics_textfile.py /tmp/_t1_trace.prom \
        --require acg_trace_ || rc=$((rc ? rc : 1))
    python - <<'PY' || rc=$((rc ? rc : 1))
import json
doc = json.load(open("/tmp/_t1_trace.json"))
assert doc["schema"] == "acg-tpu-stats/12", doc["schema"]
tr = doc["stats"]["tracing"]
tl = tr["timeline"]
assert tl["nparts"] == 8 and tl["nspans"] > 0, tl
assert "available" in tr, tr
print(f"T1_TRACE: OK ({tl['nspans']} spans over {tl['nparts']} parts, "
      f"capture analysis available={tr['available']})")
PY
fi
if [ "${T1_STATUS:-0}" = "1" ]; then
    # live-observatory smoke (the PR-9 acceptance in miniature): a
    # chunked 8-part CPU-mesh solve with the whole status plane armed
    # -- the --status-file document must validate (schema, converged
    # solve, residual-trail chunk samples), the --history ledger must
    # hold the run's row (history_report.py renders it), and the
    # declared --slo objectives must expose the acg_slo_* families
    echo "T1_STATUS: chunked 8-part status smoke"
    rm -rf /tmp/_t1_history
    rm -f /tmp/_t1_status.json /tmp/_t1_status.prom /tmp/_t1_status_ck \
        /tmp/_t1_status_stats.json
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -m acg_tpu.cli gen:poisson2d:24 --nparts 8 \
        --max-iterations 300 --residual-rtol 1e-8 --warmup 0 --quiet \
        --ckpt /tmp/_t1_status_ck --ckpt-every 16 \
        --status-file /tmp/_t1_status.json \
        --history /tmp/_t1_history \
        --slo latency=60,iters=280 \
        --metrics-file /tmp/_t1_status.prom \
        --stats-json /tmp/_t1_status_stats.json || rc=$((rc ? rc : 1))
    python scripts/check_metrics_textfile.py /tmp/_t1_status.prom \
        --require acg_slo_target --require acg_slo_burn_ratio \
        || rc=$((rc ? rc : 1))
    python scripts/history_report.py /tmp/_t1_history \
        || rc=$((rc ? rc : 1))
    python - <<'PY' || rc=$((rc ? rc : 1))
import json, os
doc = json.load(open("/tmp/_t1_status.json"))
assert doc["schema"] == "acg-tpu-status/1", doc["schema"]
assert doc["solve"]["converged"] is True, doc["solve"]
assert doc["solve"]["iteration"] > 0, doc["solve"]
assert doc["residual_trail"], "no chunk samples on the residual trail"
assert doc["slo"]["breached"] is False, doc["slo"]
ledgers = [f for f in os.listdir("/tmp/_t1_history")
           if f.endswith(".jsonl")]
assert len(ledgers) == 1, ledgers
row = json.loads(open(f"/tmp/_t1_history/{ledgers[0]}").readline())
assert row["ledger"] == "acg-tpu-history/1", row["ledger"]
assert row["nparts"] == 8 and row["converged"] is True, row
assert row["doc"]["schema"] == "acg-tpu-stats/12", row["doc"]["schema"]
sj = json.load(open("/tmp/_t1_status_stats.json"))
assert sj["stats"]["slo"]["targets"]["iters"] == 280, sj["stats"]["slo"]
print(f"T1_STATUS: OK (iteration {doc['solve']['iteration']}, "
      f"{len(doc['residual_trail'])} trail samples, ledger row "
      f"{row['case']})")
PY
fi
if [ "${T1_CHAOS:-0}" = "1" ]; then
    # elastic-recovery smoke (the ISSUE-10 acceptance in miniature):
    # (1) kill -> supervisor shrink-resume -> converged: crash:exit
    # hard-kills an 8-part checkpointed solve (rc 94), the supervisor
    # relaunches on 4 parts with --resume --resume-repartition, and
    # the answer must verify against a host-side rebuild of the
    # matrix; (2) a small seeded chaos campaign must end every
    # schedule converged-or-agreed-abort with zero wrong-answer-green
    # and the acg_recovery_* families present
    echo "T1_CHAOS: supervisor shrink-resume + seeded campaign"
    rm -rf /tmp/_t1_chaos_hist
    rm -f /tmp/_t1_chaos_ck /tmp/_t1_chaos_x.mtx /tmp/_t1_chaos.prom
    timeout -k 10 600 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -m acg_tpu.cli gen:poisson2d:20 --nparts 8 \
        --max-iterations 400 --residual-rtol 1e-8 --warmup 0 --quiet \
        --ckpt /tmp/_t1_chaos_ck --ckpt-every 8 \
        --fault-inject crash:exit@20 \
        --supervise --shrink any --relaunch-backoff 0 \
        --metrics-file /tmp/_t1_chaos.prom \
        -o /tmp/_t1_chaos_x.mtx || rc=$((rc ? rc : 1))
    python scripts/check_metrics_textfile.py /tmp/_t1_chaos.prom \
        --require acg_recovery_ || rc=$((rc ? rc : 1))
    python - <<'PY' || rc=$((rc ? rc : 1))
import numpy as np
from acg_tpu.io.generators import poisson_mtx
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.supervisor import verify_solution
csr = SymCsrMatrix.from_mtx(poisson_mtx(20, dim=2)).to_csr()
ok, rel = verify_solution(csr, np.ones(csr.shape[0]),
                          "/tmp/_t1_chaos_x.mtx", 1e-8)
assert ok, f"shrink-resumed answer fails verification ({rel:.3e})"
print(f"T1_CHAOS: shrink-resume OK (true rel residual {rel:.3e})")
PY
    timeout -k 10 900 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -m acg_tpu.cli gen:poisson2d:20 --nparts 8 \
        --max-iterations 400 --residual-rtol 1e-8 --warmup 0 --quiet \
        --ckpt /tmp/_t1_chaos_ck --ckpt-every 8 \
        --audit-every 5 --abft --shrink any \
        --chaos 1234:6 --relaunch-backoff 0 \
        --history /tmp/_t1_chaos_hist || rc=$((rc ? rc : 1))
    python - <<'PY' || rc=$((rc ? rc : 1))
import json, os
rows = []
for name in os.listdir("/tmp/_t1_chaos_hist"):
    for line in open(f"/tmp/_t1_chaos_hist/{name}"):
        obj = json.loads(line)
        if obj.get("schema") == "acg-tpu-chaos/1":
            rows.append(obj["doc"]["chaos"])
assert len(rows) == 6, len(rows)
outcomes = [r["outcome"] for r in rows]
assert "WRONG-ANSWER" not in outcomes, outcomes
print(f"T1_CHAOS: campaign OK ({outcomes.count('converged')} "
      f"converged, {outcomes.count('agreed-abort')} agreed-abort, "
      f"0 wrong-answer)")
PY
fi
if [ "${T1_BATCH:-0}" = "1" ]; then
    # batched multi-RHS smoke (the ISSUE-11 acceptance in miniature):
    # B=4 systems against one matrix on the 8-part CPU mesh, one
    # batched SPMD program -- every RHS must converge, the per-RHS
    # evidence must land in the batch: stats section, the status
    # document and the history ledger
    echo "T1_BATCH: 8-part B=4 batched smoke"
    rm -rf /tmp/_t1_batch_hist
    rm -f /tmp/_t1_batch.json /tmp/_t1_batch_status.json
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -m acg_tpu.cli gen:poisson2d:24 --nparts 8 --nrhs 4 \
        --max-iterations 400 --residual-rtol 1e-8 --warmup 0 --quiet \
        --ckpt /tmp/_t1_batch_ck --ckpt-every 20 \
        --status-file /tmp/_t1_batch_status.json \
        --history /tmp/_t1_batch_hist \
        --stats-json /tmp/_t1_batch.json || rc=$((rc ? rc : 1))
    python - <<'PY' || rc=$((rc ? rc : 1))
import json, os
doc = json.load(open("/tmp/_t1_batch.json"))
assert doc["schema"] == "acg-tpu-stats/12", doc["schema"]
batch = doc["stats"]["batch"]
assert batch["nrhs"] == 4 and len(batch["iterations"]) == 4, batch
assert all(batch["converged"]) and batch["unconverged"] == 0, batch
sd = json.load(open("/tmp/_t1_batch_status.json"))
sb = sd["solve"]["batch"]
assert sb["nrhs"] == 4 and len(sb["residuals"]) == 4, sb
ledgers = [f for f in os.listdir("/tmp/_t1_batch_hist")
           if f.endswith(".jsonl")]
row = json.loads(open(f"/tmp/_t1_batch_hist/{ledgers[0]}").readline())
assert row["doc"]["stats"]["batch"]["nrhs"] == 4, row["doc"]["stats"]
print(f"T1_BATCH: OK (per-RHS iterations {batch['iterations']}, "
      f"slowest rhs {sb['slowest_rhs']})")
PY
fi
if [ "${T1_SSTEP:-0}" = "1" ]; then
    # communication-avoiding recurrence smoke (the ISSUE-12 acceptance
    # in miniature): s-step and p(l) solves on the aniso generator over
    # the 8-part CPU mesh -- both must converge to rtol, and the comm
    # ledger in the stats twin must show the reduction-count drop
    # (sstep 1 allreduce per S iterations, p(l) 1 fused per iteration)
    echo "T1_SSTEP: 8-part s-step + p(l) smoke"
    rm -f /tmp/_t1_sstep.json /tmp/_t1_pl.json
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -m acg_tpu.cli gen:poisson2d:24 --nparts 8 \
        --algorithm sstep:4 \
        --max-iterations 2000 --residual-rtol 1e-6 --warmup 0 --quiet \
        --stats-json /tmp/_t1_sstep.json || rc=$((rc ? rc : 1))
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -m acg_tpu.cli gen:poisson2d:24 --nparts 8 \
        --algorithm pipelined:2 \
        --max-iterations 2000 --residual-rtol 1e-6 --warmup 0 --quiet \
        --stats-json /tmp/_t1_pl.json || rc=$((rc ? rc : 1))
    python - <<'PY' || rc=$((rc ? rc : 1))
import json
ss = json.load(open("/tmp/_t1_sstep.json"))
pl = json.load(open("/tmp/_t1_pl.json"))
assert ss["stats"]["converged"] is True, ss["stats"]
assert pl["stats"]["converged"] is True, pl["stats"]
# the comm-ledger reduction-count invariant, through the library's
# own ledger (recurrence.reduction_schedule feeds comm_profile)
from acg_tpu.recurrence import parse_algorithm, reduction_schedule
s4 = reduction_schedule(parse_algorithm("sstep:4"), False)
p2 = reduction_schedule(parse_algorithm("pipelined:2"), False)
assert s4["allreduce_per_iteration"] == 0.25, s4
assert p2["allreduce_per_iteration"] == 1.0, p2
assert p2["reduction_latency_hidden"] == 2, p2
print(f"T1_SSTEP: OK (sstep {ss['stats']['niterations']} its, "
      f"p(2) {pl['stats']['niterations']} its, both converged; "
      f"sstep:4 {s4['allreduce_per_iteration']} allreduce/iter)")
PY
fi
if [ "${T1_FUSED:-0}" = "1" ]; then
    # fused-overlap smoke (the ISSUE-13 acceptance in miniature): an
    # 8-part interpret-mode fused solve (interior/border overlapped
    # SpMV, --kernels fused) must converge; then the armed-pin +
    # overlap-section asserts -- the fused program keeps the unsplit
    # tier's collective inventory (5 all_reduces / 2 all_to_alls,
    # comm=dma drops the all_to_alls), kernels=auto stays
    # byte-identical to xla, and the comm ledger declares the
    # interior|border overlap model
    echo "T1_FUSED: 8-part fused overlap smoke"
    rm -f /tmp/_t1_fused.json
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -m acg_tpu.cli gen:poisson2d:24 --nparts 8 \
        --kernels fused --max-iterations 400 --residual-rtol 1e-8 \
        --warmup 0 --quiet --stats-json /tmp/_t1_fused.json \
        || rc=$((rc ? rc : 1))
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python - <<'PY' || rc=$((rc ? rc : 1))
import json, re
import numpy as np
import jax.numpy as jnp
doc = json.load(open("/tmp/_t1_fused.json"))
assert doc["stats"]["converged"] is True, doc["stats"]["rnrm2"]
from acg_tpu.io.generators import poisson2d_coo
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
from acg_tpu.partition import partition_rows
r, c, v, N = poisson2d_coo(16)
csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
part = partition_rows(csr, 8, seed=0, method="band")
prob = DistributedProblem.build(csr, part, 8, dtype=jnp.float32)
b = np.ones(N)
fused = DistCGSolver(prob, kernels="fused")
txt = fused.lower_solve(b).as_text()
ar = len(re.findall(r"all_reduce", txt))
ata = len(re.findall(r"all_to_all", txt))
assert (ar, ata) == (5, 2), (ar, ata)
dtxt = DistCGSolver(prob, kernels="fused",
                    comm="dma").lower_solve(b).as_text()
assert len(re.findall(r"all_to_all", dtxt)) == 0
auto = DistCGSolver(prob, kernels="auto").lower_solve(b).as_text()
xla = DistCGSolver(prob, kernels="xla").lower_solve(b).as_text()
assert auto == xla, "kernels=auto no longer byte-identical to xla"
ov = fused.comm_profile()["overlap"]
assert ov["split"] == "interior|border", ov
assert ov["interior_rows"] > 0 and ov["border_rows"] > 0, ov
print(f"T1_FUSED: OK (converged, pins (5,2)/dma-0-a2a hold, "
      f"{ov['interior_rows']} interior / {ov['border_rows']} border "
      f"rows)")
PY
fi
if [ "${T1_COMMBENCH:-0}" = "1" ]; then
    # communication-observatory smoke (the ISSUE-14 acceptance in
    # miniature): an 8-part --commbench sweep must emit an
    # acg-tpu-commbench/1 document that round-trips the validator
    # (fitted alpha-beta per collective kind, per-edge DMA rows,
    # measured segment decomposition), and a calibrated --explain on
    # the same case must print calibration provenance and land its
    # predicted-vs-measured ratio strictly closer to 1.0 than the
    # uncalibrated model's
    echo "T1_COMMBENCH: 8-part commbench + calibrated explain smoke"
    rm -f /tmp/_t1_cb.json /tmp/_t1_cb_explain.jsonl
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -m acg_tpu.cli gen:poisson2d:16 --nparts 8 \
        --dtype f32 --max-iterations 20 --warmup 0 --quiet \
        --commbench /tmp/_t1_cb.json || rc=$((rc ? rc : 1))
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -m acg_tpu.cli gen:poisson2d:16 --nparts 8 \
        --dtype f32 --max-iterations 20 --warmup 0 --quiet \
        --explain --calibration /tmp/_t1_cb.json \
        --stats-json /tmp/_t1_cb_explain.jsonl \
        2> /tmp/_t1_cb_explain.err || rc=$((rc ? rc : 1))
    python - <<'PY' || rc=$((rc ? rc : 1))
import json, math
from acg_tpu.commbench import validate_commbench
doc = json.load(open("/tmp/_t1_cb.json"))
assert validate_commbench(doc) == [], validate_commbench(doc)
for kind in ("all_reduce", "all_to_all", "collective_permute", "dma"):
    assert "alpha_s" in doc["collectives"][kind], kind
assert [e["distance"] for e in doc["edges"]] == [1, 2, 3, 4]
assert doc["segments"]["available"] is True, doc["segments"]
err = open("/tmp/_t1_cb_explain.err").read()
assert "== explain: calibration ==" in err
assert doc["calibration_id"] in err
docs = [json.loads(ln) for ln in
        open("/tmp/_t1_cb_explain.jsonl") if ln.strip()]
dist = [d for d in docs if "dist-cg" in d["manifest"]["metric"]]
assert dist and dist[0]["manifest"]["calibration"] \
    == doc["calibration_id"]
row = dist[0]["manifest"]["explain"]
ratio = row["predicted_s_per_iter"] / row["measured_s_per_iter"]
uncal = (row["uncalibrated_predicted_s_per_iter"]
         / row["measured_s_per_iter"])
assert abs(math.log(ratio)) < abs(math.log(uncal)), (ratio, uncal)
print(f"T1_COMMBENCH: OK (id {doc['calibration_id']}, calibrated "
      f"ratio {ratio:.2f}x vs uncalibrated {uncal:.2f}x)")
PY
fi
if [ "${T1_MATFREE:-0}" = "1" ]; then
    # matrix-free operator smoke (the ISSUE-15 acceptance in
    # miniature): an 8-part mesh stencil solve with --operator stencil
    # must converge, its printed solution must be BYTE-IDENTICAL to
    # the assembled run's (the bitwise-trajectory contract observed
    # end to end), the stats manifest must carry the operator
    # identity, and the comm ledger must declare matrix_free with a
    # zero matrix-bytes term
    echo "T1_MATFREE: 8-part matrix-free stencil smoke"
    rm -f /tmp/_t1_mf_a.mtx /tmp/_t1_mf_m.mtx /tmp/_t1_mf.json
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -m acg_tpu.cli gen:poisson2d:24 --nparts 8 \
        --max-iterations 300 --residual-rtol 1e-8 --warmup 0 --quiet \
        -o /tmp/_t1_mf_a.mtx || rc=$((rc ? rc : 1))
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -m acg_tpu.cli gen:poisson2d:24 --nparts 8 \
        --operator stencil \
        --max-iterations 300 --residual-rtol 1e-8 --warmup 0 --quiet \
        -o /tmp/_t1_mf_m.mtx \
        --stats-json /tmp/_t1_mf.json || rc=$((rc ? rc : 1))
    cmp -s /tmp/_t1_mf_a.mtx /tmp/_t1_mf_m.mtx || {
        echo "T1_MATFREE: matrix-free solution differs from assembled"
        rc=$((rc ? rc : 1)); }
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python - <<'PY' || rc=$((rc ? rc : 1))
import json
import numpy as np
import jax.numpy as jnp
doc = json.load(open("/tmp/_t1_mf.json"))
assert doc["schema"] == "acg-tpu-stats/12", doc["schema"]
st = doc["stats"]
assert st["converged"] is True, st["rnrm2"]
assert doc["manifest"]["operator"] == "stencil:poisson2d:24", \
    doc["manifest"]
assert doc["manifest"]["partition"]["local_format"] == "matfree", \
    doc["manifest"]["partition"]
from acg_tpu.io.generators import poisson2d_coo
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.ops.operator import poisson_stencil
from acg_tpu.parallel.dist import (DistCGSolver, DistributedProblem,
                                   arm_matfree)
from acg_tpu.partition import partition_rows
r, c, v, N = poisson2d_coo(24)
csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
part = partition_rows(csr, 8, seed=0, method="band")
prob = DistributedProblem.build(csr, part, 8, dtype=jnp.float64)
arm_matfree(prob, poisson_stencil(24, 2, dtype=jnp.float64))
led = DistCGSolver(prob).comm_profile()
assert led["matrix_free"] is True, led
assert led["operator"] == "stencil:poisson2d:24", led
assert led["matrix_bytes_per_spmv"] == 0, led
print(f"T1_MATFREE: OK (converged in {st['niterations']} iterations, "
      f"byte-identical to assembled, ledger matrix-bytes 0)")
PY
fi
if [ "${T1_PLAN:-0}" = "1" ]; then
    # decision-observatory smoke (the ISSUE-17 acceptance in
    # miniature): calibrate the mesh, then let --autotune choose the
    # program numerically; the ranked plan document must validate
    # with calibration provenance, the planned solve must leave a
    # plan-vs-actual row in the history ledger (history_report.py
    # renders the plan column), and the acg_plan_* metric families
    # must land in the metrics textfile
    echo "T1_PLAN: 8-part commbench -> autotune -> plan-vs-actual smoke"
    rm -rf /tmp/_t1_plan_hist
    rm -f /tmp/_t1_plan_cal.json /tmp/_t1_plan.json \
        /tmp/_t1_plan_stats.json /tmp/_t1_plan.prom
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -m acg_tpu.cli gen:poisson2d:16 --nparts 8 \
        --dtype f32 --max-iterations 20 --warmup 0 --quiet \
        --commbench /tmp/_t1_plan_cal.json || rc=$((rc ? rc : 1))
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -m acg_tpu.cli gen:poisson2d:32 --nparts 8 \
        --autotune --calibration /tmp/_t1_plan_cal.json \
        --plan /tmp/_t1_plan.json --history /tmp/_t1_plan_hist \
        --stats-json /tmp/_t1_plan_stats.json \
        --metrics-file /tmp/_t1_plan.prom \
        --residual-rtol 1e-6 --max-iterations 300 --warmup 0 \
        --quiet 2> /tmp/_t1_plan.err || rc=$((rc ? rc : 1))
    python - <<'PY' || rc=$((rc ? rc : 1))
import json
from acg_tpu.planner import validate_plan
from acg_tpu.observatory import history_scan
cal = json.load(open("/tmp/_t1_plan_cal.json"))
doc = json.load(open("/tmp/_t1_plan.json"))
assert validate_plan(doc) == [], validate_plan(doc)
assert doc["calibration"] == cal["calibration_id"], doc["calibration"]
assert doc["uncalibrated"] is False and doc["ranked"]
err = open("/tmp/_t1_plan.err").read()
assert "autotune: dispatching" in err, err
sj = json.load(open("/tmp/_t1_plan_stats.json"))
assert sj["schema"] == "acg-tpu-stats/12", sj["schema"]
plan = sj["stats"]["plan"]
assert plan["plan_id"] == doc["plan_id"], plan
assert plan["source"] in ("planned", "fallback"), plan
assert plan["measured_s_per_solve"] > 0, plan
rows = [e["doc"]["stats"]["plan"] for e in
        history_scan("/tmp/_t1_plan_hist")
        if (e.get("doc") or {}).get("stats", {}).get("plan")]
assert rows and rows[-1]["plan_id"] == doc["plan_id"], rows
print(f"T1_PLAN: OK (plan {doc['plan_id']}, source {plan['source']}, "
      f"selected {plan.get('selected')}, misprediction "
      f"{plan.get('misprediction_ratio', 0):.2f}x)")
PY
    python scripts/history_report.py /tmp/_t1_plan_hist \
        | grep -q "plan x" || {
        echo "T1_PLAN: history_report plan column missing"
        rc=$((rc ? rc : 1)); }
    python scripts/check_metrics_textfile.py /tmp/_t1_plan.prom \
        --require acg_plan_decisions_total \
        --require acg_plan_misprediction_ratio || rc=$((rc ? rc : 1))
fi
if [ "${T1_SERVE:-0}" = "1" ]; then
    # solver-service smoke (the ISSUE-16 acceptance in miniature): a
    # supervised 8-part --serve daemon -- two identical requests (the
    # second must hit BOTH caches and leave acg_compiles_total
    # untouched), one coalesced pair, a crash-mid-request relaunch
    # with warm operator-cache restore, and a clean shutdown
    echo "T1_SERVE: supervised 8-part solver-service smoke"
    rm -f /tmp/_t1_serve_ck /tmp/_t1_serve_ck.serve.json
    SERVE_PORT=$((20000 + RANDOM % 20000))
    timeout -k 10 600 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -m acg_tpu.cli gen:poisson2d:20 --nparts 8 \
        --serve --serve-port "$SERVE_PORT" --serve-faults \
        --supervise --relaunch-backoff 0 --quiet \
        --ckpt /tmp/_t1_serve_ck &
    SERVE_PID=$!
    env SERVE_PORT="$SERVE_PORT" python - <<'PY' || rc=$((rc ? rc : 1))
import json, os, threading, time, urllib.request

base = f"http://127.0.0.1:{os.environ['SERVE_PORT']}"


def req(method, path, doc=None, timeout=180.0):
    r = urllib.request.Request(
        base + path, method=method,
        data=None if doc is None else json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read().decode())


def counter(name):
    with urllib.request.urlopen(base + "/metrics",
                                timeout=30.0) as resp:
        text = resp.read().decode()
    total = 0.0
    for line in text.splitlines():
        head, _, val = line.rpartition(" ")
        if not line.startswith("#") and (
                head == name or head.startswith(name + "{")):
            total += float(val)
    return total


def wait_up(budget=240.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < budget:
        try:
            s, d = req("GET", "/healthz", timeout=5.0)
            if s == 200 and d.get("ok"):
                return True
        except Exception:
            pass
        time.sleep(0.25)
    return False


assert wait_up(), "T1_SERVE: the daemon never came up"
doc = {"b_seed": 1, "rtol": 1e-8, "maxits": 500}
s, b1 = req("POST", "/solve", doc)
assert s == 200 and b1["ok"] and b1["converged"], b1
# the daemon preloads its boot operator; only the program is cold
assert b1["cache"] == {"operator": "hit", "program": "miss"}, b1
c1 = counter("acg_compiles_total")
s, b2 = req("POST", "/solve", dict(doc, b_seed=2))
assert s == 200 and b2["ok"], b2
assert b2["cache"] == {"operator": "hit", "program": "hit"}, b2
c2 = counter("acg_compiles_total")
assert c2 == c1, f"repeat request recompiled ({c1} -> {c2})"

# one coalesced pair: hold the worker with a slow (uncoalescible)
# request, race two compatible followers into the queue
results = {}


def fire(key, body):
    results[key] = req("POST", "/solve", body)


ts = [threading.Thread(target=fire, args=(
    "slow", dict(doc, b_seed=9, fault="slow:0.8")))]
ts[0].start()
time.sleep(0.4)
for seed in (11, 12):
    t = threading.Thread(target=fire, args=(seed, dict(doc,
                                                       b_seed=seed)))
    ts.append(t)
    t.start()
for t in ts:
    t.join(timeout=240.0)
for seed in (11, 12):
    s, body = results[seed]
    assert s == 200 and body["coalesced"] == 2, (seed, body)

# crash mid-request -> supervisor relaunch -> warm restore
try:
    req("POST", "/solve", dict(doc, fault="crash"), timeout=30.0)
except Exception:
    pass  # the connection dies with the daemon
assert wait_up(), "T1_SERVE: the daemon did not relaunch"
s, st = req("GET", "/status")
assert st["warm_restored"] >= 1, st
s, b3 = req("POST", "/solve", dict(doc, b_seed=3))
assert s == 200 and b3["ok"], b3
assert b3["cache"]["operator"] == "hit", b3

req("POST", "/shutdown", {}, timeout=10.0)
print("T1_SERVE: OK (zero-recompile repeat, coalesced pair of 2, "
      "crash relaunch + warm restore, clean shutdown)")
PY
    wait "$SERVE_PID"
    serve_rc=$?
    if [ "$serve_rc" != "0" ]; then
        echo "T1_SERVE: supervised daemon exited $serve_rc (want 0)"
        rc=$((rc ? rc : 1))
    fi
fi
if [ "${T1_REQTRACE:-0}" = "1" ]; then
    # request-observatory smoke (the ISSUE-18 acceptance in
    # miniature): identity echo (client id + traceparent), a coalesced
    # pair attributed per RHS in the access ledger, the /requests
    # ring, the service timeline, and the three CI gates over the
    # artifacts the daemon leaves behind
    echo "T1_REQTRACE: 8-part request-observatory smoke"
    rm -f /tmp/_t1_reqtrace.jsonl /tmp/_t1_reqtrace_tl.json \
        /tmp/_t1_reqtrace.prom
    RT_PORT=$((20000 + RANDOM % 20000))
    timeout -k 10 600 env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -m acg_tpu.cli gen:poisson2d:20 --nparts 8 \
        --serve --serve-port "$RT_PORT" --serve-faults --quiet \
        --access-log /tmp/_t1_reqtrace.jsonl \
        --timeline /tmp/_t1_reqtrace_tl.json &
    RT_PID=$!
    env RT_PORT="$RT_PORT" python - <<'PY' || rc=$((rc ? rc : 1))
import json, os, threading, time, urllib.request

base = f"http://127.0.0.1:{os.environ['RT_PORT']}"


def req(method, path, doc=None, timeout=180.0):
    r = urllib.request.Request(
        base + path, method=method,
        data=None if doc is None else json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read().decode())


def wait_up(budget=240.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < budget:
        try:
            s, d = req("GET", "/healthz", timeout=5.0)
            if s == 200 and d.get("ok"):
                return True
        except Exception:
            pass
        time.sleep(0.25)
    return False


assert wait_up(), "T1_REQTRACE: the daemon never came up"
doc = {"b_seed": 1, "rtol": 1e-8, "maxits": 500}
s, b1 = req("POST", "/solve", dict(doc, request_id="smoke-1"))
assert s == 200 and b1["ok"] and b1["request_id"] == "smoke-1", b1
tp = "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01"
s, b2 = req("POST", "/solve", dict(doc, b_seed=2, traceparent=tp))
assert b2["request_id"] == tp.split("-")[1], b2

# the coalesced pair: hold the worker with a slow (uncoalescible)
# lead, race two identified followers into the queue
results = {}


def fire(key, body):
    results[key] = req("POST", "/solve", body)


ts = [threading.Thread(target=fire, args=(
    "slow", dict(doc, b_seed=9, fault="slow:0.8",
                 request_id="smoke-slow")))]
ts[0].start()
time.sleep(0.4)
for seed in (11, 12):
    t = threading.Thread(target=fire, args=(
        seed, dict(doc, b_seed=seed,
                   request_id=f"smoke-pair-{seed}")))
    ts.append(t)
    t.start()
for t in ts:
    t.join(timeout=240.0)
for seed in (11, 12):
    s, body = results[seed]
    assert s == 200 and body["coalesced"] == 2, (seed, body)
    assert body["request_id"] == f"smoke-pair-{seed}", body

s, ring = req("GET", "/requests")
assert ring["schema"] == "acg-serve-requests/1", ring
done = {d["request_id"] for d in ring["completed"]}
assert {"smoke-1", "smoke-pair-11", "smoke-pair-12"} <= done, done
s, st = req("GET", "/status")
blk = st["requests"]
assert blk["completed"] >= 5 and blk["outcomes"]["ok"] >= 5, blk
assert blk["access_log"] == "/tmp/_t1_reqtrace.jsonl", blk

with urllib.request.urlopen(base + "/metrics",
                            timeout=30.0) as resp:
    expo = resp.read().decode()
with open("/tmp/_t1_reqtrace.prom", "w") as f:
    f.write(expo)

req("POST", "/shutdown", {}, timeout=10.0)
print("T1_REQTRACE: OK (identity echo incl. traceparent, coalesced "
      "pair of 2, /requests ring + requests: block, clean shutdown)")
PY
    wait "$RT_PID"
    rt_rc=$?
    if [ "$rt_rc" != "0" ]; then
        echo "T1_REQTRACE: daemon exited $rt_rc (want 0)"
        rc=$((rc ? rc : 1))
    fi
    python scripts/check_access_log.py /tmp/_t1_reqtrace.jsonl \
        --min-rows 5 --require-outcome ok || rc=$((rc ? rc : 1))
    python scripts/access_report.py /tmp/_t1_reqtrace.jsonl \
        --fail-on-p99 60 | grep -q "p99" || rc=$((rc ? rc : 1))
    # the latency gate must actually gate: an absurd budget trips 7
    python scripts/access_report.py /tmp/_t1_reqtrace.jsonl \
        --fail-on-p99 0.000001 >/dev/null 2>&1
    if [ "$?" != "7" ]; then
        echo "T1_REQTRACE: --fail-on-p99 did not exit 7"
        rc=$((rc ? rc : 1))
    fi
    python scripts/check_timeline.py /tmp/_t1_reqtrace_tl.json \
        || rc=$((rc ? rc : 1))
    python scripts/check_metrics_textfile.py /tmp/_t1_reqtrace.prom \
        --require acg_serve_stage_seconds \
        --require acg_serve_inflight || rc=$((rc ? rc : 1))
fi
exit $rc
