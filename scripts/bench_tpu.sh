#!/usr/bin/env bash
# Single-chip TPU benchmark sweep: solver x dtype on the reference
# workload (2D Poisson n=2048, 4.19M unknowns, 1000 iterations) -- the
# protocol of scripts/nccl_combined.sh at np=1, plus the TPU-specific
# precision variants (f32, f32+refine, f64).
#
# Usage: scripts/bench_tpu.sh [N_SIDE]

set -euo pipefail
cd "$(dirname "$0")/.."

N=${1:-2048}
WORKDIR=$(mktemp -d)
trap 'rm -rf "$WORKDIR"' EXIT
export PYTHONPATH=${PYTHONPATH:-$PWD}

MTX="$WORKDIR/poisson2d_n$N.mtx"
echo "# generating 2D Poisson n=$N"
python -m acg_tpu.tools.genmatrix -n "$N" --dim 2 -o "$MTX"

run() {
    echo "=== $* ==="
    python -m acg_tpu.cli "$MTX" --comm none --warmup 1 --quiet \
        --manufactured-solution "$@" 2>&1 |
        grep -E "total solver time|total flop rate|iterations:|error 2-norm" |
        sed 's/^/    /'
}

# fixed-iteration throughput (rtol 0 = unbounded benchmark mode)
run --solver acg --dtype f32 --max-iterations 1000 --residual-rtol 0
run --solver acg-pipelined --dtype f32 --max-iterations 1000 --residual-rtol 0
# time-to-tolerance
run --solver acg --dtype f32 --max-iterations 20000 --residual-rtol 1e-6
run --solver acg --dtype f32 --refine --max-iterations 20000 --residual-rtol 1e-11
run --solver acg --dtype f64 --max-iterations 2000 --residual-rtol 1e-6

# north-star problem: 3D 512^3 via zero-transfer on-device assembly
# (gen: spec; see BASELINE.md) -- single chip, f32
echo "=== gen:poisson3d:512 (N=134M) classic f32 ==="
python -m acg_tpu.cli gen:poisson3d:512 --dtype f32 --comm none \
    --max-iterations 1000 --residual-rtol 0 --warmup 1 --quiet 2>&1 |
    grep -E "total solver time|total flop rate|iterations:" | sed 's/^/    /'
