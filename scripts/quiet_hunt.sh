#!/bin/bash
# Hunt for an HONEST quiet window: only run the quiet_ab capture when
# (a) block_until_ready actually waits (no fetch-RTT jitter in the
# timings) and (b) the bandwidth probe clears the quiet threshold.
# Sleeps between attempts; bounded total duration.
#
# Usage: scripts/quiet_hunt.sh [TOTAL_SECONDS] [SLEEP_SECONDS]
set -u
cd "$(dirname "$0")/.."
TOTAL=${1:-14400}
NAP=${2:-900}
deadline=$(( $(date +%s) + TOTAL ))

while [ "$(date +%s)" -lt "$deadline" ]; do
  honest=$(timeout 300 python -c "
from acg_tpu._platform import block_until_ready_works
print('yes' if block_until_ready_works() else 'no')" 2>/dev/null | tail -1)
  if [ "$honest" = "yes" ]; then
    echo "# $(date -u +%H:%M:%S) block honest -- attempting capture" >&2
    timeout 2400 python scripts/quiet_ab.py --min-bw 600 --pairs 3 \
      --wait-budget 300 && exit 0
  else
    echo "# $(date -u +%H:%M:%S) backend still degraded (honest=$honest)" >&2
  fi
  sleep "$NAP"
done
echo "# quiet hunt: no honest window within budget" >&2
exit 3
