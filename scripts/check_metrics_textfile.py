#!/usr/bin/env python3
"""Validate a ``--metrics-file`` Prometheus textfile.

The CI soak-smoke's assertion (and a handy operator check): every line
must be exposition-format 0.0.4 -- ``# HELP``/``# TYPE`` comments or
``name{labels} value`` samples -- histograms must have monotone
cumulative buckets whose ``+Inf`` count equals ``_count``, and any
series named on the command line must be present.

Usage:
    python scripts/check_metrics_textfile.py FILE [--require NAME ...]

Exit 0 = valid, 1 = malformed (each problem named on stderr).
"""

from __future__ import annotations

import argparse
import math
import re
import sys

_SAMPLE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?P<labels>\{[^{}]*\})?\s+(?P<value>\S+)(\s+\d+)?$')
_LABEL = re.compile(r'^[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"$')


def _parse_value(text: str) -> float:
    if text == "+Inf":
        return math.inf
    if text == "-Inf":
        return -math.inf
    if text == "NaN":
        return math.nan
    return float(text)


def check(path: str, require=()) -> list[str]:
    problems: list[str] = []
    typed: dict[str, str] = {}
    buckets: dict[str, dict[tuple, list]] = {}
    counts: dict[str, dict[tuple, float]] = {}
    seen: set[str] = set()
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.rstrip("\n")
            if not line.strip():
                continue
            if line.startswith("#"):
                parts = line.split(None, 3)
                if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                    problems.append(f"{path}:{lineno}: malformed "
                                    f"comment: {line!r}")
                elif parts[1] == "TYPE":
                    if parts[3] not in ("counter", "gauge", "histogram",
                                        "summary", "untyped"):
                        problems.append(f"{path}:{lineno}: unknown "
                                        f"type {parts[3]!r}")
                    typed[parts[2]] = parts[3]
                continue
            m = _SAMPLE.match(line)
            if not m:
                problems.append(f"{path}:{lineno}: malformed sample: "
                                f"{line!r}")
                continue
            name = m.group("name")
            labels = m.group("labels")
            lab_pairs = []
            if labels:
                for pair in _split_labels(labels[1:-1]):
                    if not _LABEL.match(pair):
                        problems.append(f"{path}:{lineno}: malformed "
                                        f"label {pair!r}")
                    lab_pairs.append(pair)
            try:
                value = _parse_value(m.group("value"))
            except ValueError:
                problems.append(f"{path}:{lineno}: non-numeric value "
                                f"{m.group('value')!r}")
                continue
            seen.add(name)
            base = re.sub(r"_(bucket|sum|count)$", "", name)
            seen.add(base)
            if typed.get(base) == "histogram":
                key = tuple(p for p in lab_pairs
                            if not p.startswith("le="))
                if name.endswith("_bucket"):
                    le = [p for p in lab_pairs if p.startswith("le=")]
                    if not le:
                        problems.append(f"{path}:{lineno}: histogram "
                                        f"bucket without le label")
                        continue
                    ub = _parse_value(le[0][4:-1])
                    buckets.setdefault(base, {}).setdefault(
                        key, []).append((ub, value, lineno))
                elif name.endswith("_count"):
                    counts.setdefault(base, {})[key] = value
    for base, by_series in buckets.items():
        for key, rows in by_series.items():
            rows.sort()
            cum = [v for _, v, _ in rows]
            if any(b > a for a, b in zip(cum[1:], cum)):
                problems.append(f"{path}: {base}{list(key)}: bucket "
                                f"counts not monotone: {cum}")
            if not rows or not math.isinf(rows[-1][0]):
                problems.append(f"{path}: {base}{list(key)}: missing "
                                f"+Inf bucket")
            elif counts.get(base, {}).get(key) is not None \
                    and rows[-1][1] != counts[base][key]:
                problems.append(
                    f"{path}: {base}{list(key)}: +Inf bucket "
                    f"{rows[-1][1]} != _count {counts[base][key]}")
    for name in require:
        # exact series name, or a family prefix (trailing '_'):
        # --require acg_ckpt_ asserts the whole family exposed
        if name not in seen and not (
                name.endswith("_")
                and any(s.startswith(name) for s in seen)):
            problems.append(f"{path}: required series {name!r} absent")
    return problems


def _split_labels(body: str) -> list[str]:
    """Split ``a="x",b="y,z"`` on commas outside quotes."""
    out, cur, in_q, esc = [], [], False, False
    for ch in body:
        if esc:
            cur.append(ch)
            esc = False
            continue
        if ch == "\\":
            cur.append(ch)
            esc = True
            continue
        if ch == '"':
            in_q = not in_q
            cur.append(ch)
            continue
        if ch == "," and not in_q:
            out.append("".join(cur))
            cur = []
            continue
        cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate a Prometheus metrics textfile "
                    "(--metrics-file output)")
    ap.add_argument("file", help="textfile to validate")
    ap.add_argument("--require", action="append", default=[],
                    metavar="NAME",
                    help="fail unless this metric family is present "
                         "(repeatable)")
    args = ap.parse_args(argv)
    try:
        problems = check(args.file, args.require)
    except OSError as e:
        print(f"check-metrics: {e}", file=sys.stderr)
        return 1
    for p in problems:
        print(f"check-metrics: {p}", file=sys.stderr)
    if not problems:
        print(f"check-metrics: {args.file}: OK")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
