#!/usr/bin/env bash
# Capture a jax.profiler trace of a solve -- the role of the reference's
# scripts/trace_{mpi,nvshmem}.sh (nsys profile -t cuda,nvtx): the trace
# contains the XLA op timeline with the solver's named scopes; view with
# xprof/tensorboard.
#
# Usage: scripts/trace.sh [TRACE_DIR] [extra acg-tpu args...]

set -euo pipefail
cd "$(dirname "$0")/.."

TRACE_DIR=${1:-/tmp/acg-tpu-trace}
shift || true
WORKDIR=$(mktemp -d)
trap 'rm -rf "$WORKDIR"' EXIT
export PYTHONPATH=${PYTHONPATH:-$PWD}

MTX="$WORKDIR/poisson2d.mtx"
python -m acg_tpu.tools.genmatrix -n 512 --dim 2 -o "$MTX"

python -m acg_tpu.cli "$MTX" --comm none --solver acg --dtype f32 \
    --max-iterations 200 --residual-rtol 0 --warmup 1 --quiet \
    --trace "$TRACE_DIR" "$@"
echo "trace written to $TRACE_DIR"
