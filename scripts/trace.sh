#!/usr/bin/env bash
# Trace harness: solver x comm sweep under profiler capture -- the role
# of the reference's scripts/trace_mpi.sh / trace_nvshmem.sh, which wrap
# every solver variant in `nsys profile -t cuda,nvtx` and leave one
# .nsys-rep per (solver, transport) cell.
#
# Mapping from the nsys workflow:
#   nsys profile -t cuda,nvtx ./acg-cuda ...   ->  --trace DIR
#       (jax.profiler capture: XLA op timeline + the solver's acg:*
#        phase annotations, the NVTX-range analog; view with xprof/
#        tensorboard, or summarise with scripts/trace_report.py DIR)
#   nsys stats / the GUI timeline               ->  --timeline FILE
#       (cross-rank span timeline as Chrome trace-event JSON, one pid
#        per part; load in Perfetto / chrome://tracing, validate with
#        scripts/check_timeline.py, summarise with trace_report.py)
#   trace_mpi.sh vs trace_nvshmem.sh            ->  the COMM axis below
#       (xla collectives vs pallas remote DMA; `none` = single chip)
#
# Output layout: $OUT/<solver>-<comm>/capture/  (profiler capture)
#                $OUT/<solver>-<comm>/timeline.json
#                $OUT/<solver>-<comm>/stats.json
#
# Usage: scripts/trace.sh [OUT_DIR] [extra acg-tpu args...]
#   TRACE_SOLVERS="acg acg-pipelined"  override the solver axis
#   TRACE_COMMS="none xla"             override the comm axis
#   TRACE_SPEC=gen:poisson2d:64        override the test system
#   TRACE_NPARTS=0                     mesh size for comm != none
#                                      (0 = all local devices)

set -euo pipefail
cd "$(dirname "$0")/.."
export PYTHONPATH=${PYTHONPATH:-$PWD}

OUT=${1:-/tmp/acg-tpu-trace}
shift || true

SOLVERS=${TRACE_SOLVERS:-"acg acg-pipelined"}
COMMS=${TRACE_COMMS:-"none xla"}
SPEC=${TRACE_SPEC:-gen:poisson2d:64}
NPARTS=${TRACE_NPARTS:-0}

for solver in $SOLVERS; do
    for comm in $COMMS; do
        cell="$OUT/$solver-$comm"
        mkdir -p "$cell"
        args=(--solver "$solver" --comm "$comm" --dtype f32
              --max-iterations 200 --residual-rtol 0 --warmup 1 --quiet
              --trace "$cell/capture" --timeline "$cell/timeline.json"
              --stats-json "$cell/stats.json")
        if [ "$comm" != "none" ] && [ "$NPARTS" != "1" ]; then
            args+=(--nparts "$NPARTS")
        fi
        echo "== trace: $solver / $comm =="
        python -m acg_tpu.cli "$SPEC" "${args[@]}" "$@"
        python scripts/check_timeline.py "$cell/timeline.json"
        python scripts/trace_report.py "$cell/capture" || true
        python scripts/trace_report.py "$cell/timeline.json"
    done
done
echo "traces written under $OUT (load timeline.json files in Perfetto)"
