#!/usr/bin/env python3
"""Validate an ``--access-log`` request ledger (``acg-tpu-access/1``).

The access ledger is the solver service's one-row-per-request record
of where the latency went; this validator is its CI gate, in the
``check_metrics_textfile.py`` / ``check_timeline.py`` family.  Checks,
stdlib only:

* every non-empty line parses as a JSON object carrying the
  ``acg-tpu-access`` schema marker and a non-empty ``request_id``;
* ``outcome`` is in the closed enum (``ok``, ``deadline-expired``,
  ``request-failed``, ``invalid-request``, or the ``shed-*`` family);
* stage names come from the service's stage vocabulary, stage seconds
  are finite and non-negative, and their sum never exceeds the row's
  ``wall_seconds`` (plus a small clock-jitter epsilon) -- attribution
  must never invent time;
* timestamps are self-consistent (``t_done >= t_arrival``) and
  ``t_done`` is strictly monotone in FILE order -- the atomic-append
  writer's contract;
* a ``batch`` block's ``width`` matches its ``members`` list, every
  member references a ``request_id`` present in the ledger, and the
  per-RHS attribution satisfies ``rhs_solve_seconds * width ~=
  solve_seconds``.

Exit codes: 0 = valid, 1 = validation failures, 2 = unreadable file.

Usage:
    python scripts/check_access_log.py access.jsonl [more.jsonl ...] \
        [--min-rows N] [--require-outcome ok]
"""

from __future__ import annotations

import argparse
import json
import math
import sys

SCHEMA_PREFIX = "acg-tpu-access"
STAGES = ("admit", "queue-wait", "coalesce", "cache", "compile",
          "solve", "demux", "respond")
OUTCOMES = ("ok", "deadline-expired", "request-failed",
            "invalid-request")
# stage sums ride two clock reads per stage; give them a small slack
EPS = 5e-3


def _load_rows(path):
    """``(rows, errors)`` -- each row tagged with its 1-based line."""
    rows, errors = [], []
    with open(path) as f:
        for lineno, raw in enumerate(f, 1):
            raw = raw.strip()
            if not raw:
                continue
            try:
                obj = json.loads(raw)
            except ValueError as e:
                errors.append(f"line {lineno}: unparseable JSON ({e})")
                continue
            if not isinstance(obj, dict):
                errors.append(f"line {lineno}: not a JSON object")
                continue
            rows.append((lineno, obj))
    return rows, errors


def validate(rows, min_rows: int = 0,
             require_outcomes=()) -> list:
    """Validate ``[(lineno, row), ...]``; returns error strings."""
    errors = []
    if len(rows) < max(int(min_rows), 0):
        errors.append(f"expected at least {min_rows} row(s), found "
                      f"{len(rows)}")
    all_ids = {str(row.get("request_id"))
               for _ln, row in rows if row.get("request_id")}
    seen_outcomes = set()
    prev_done = None
    for ln, row in rows:
        schema = str(row.get("schema", ""))
        if not schema.startswith(SCHEMA_PREFIX):
            errors.append(f"line {ln}: schema {schema!r} is not "
                          f"{SCHEMA_PREFIX}/*")
            continue
        rid = row.get("request_id")
        if not isinstance(rid, str) or not rid:
            errors.append(f"line {ln}: missing/empty request_id")
        outcome = str(row.get("outcome", ""))
        seen_outcomes.add(outcome)
        if outcome not in OUTCOMES and not outcome.startswith("shed-"):
            errors.append(f"line {ln}: outcome {outcome!r} is not in "
                          f"the ledger enum")
        stages = row.get("stages")
        if not isinstance(stages, dict):
            errors.append(f"line {ln}: missing stages object")
            stages = {}
        total = 0.0
        for name, sec in stages.items():
            if name not in STAGES:
                errors.append(f"line {ln}: unknown stage {name!r}")
            if not isinstance(sec, (int, float)) \
                    or not math.isfinite(sec) or sec < 0:
                errors.append(f"line {ln}: stage {name} seconds "
                              f"{sec!r} is not a finite non-negative "
                              f"number")
            else:
                total += float(sec)
        wall = row.get("wall_seconds")
        if not isinstance(wall, (int, float)) \
                or not math.isfinite(wall) or wall < 0:
            errors.append(f"line {ln}: bad wall_seconds {wall!r}")
        elif total > float(wall) + EPS:
            errors.append(f"line {ln}: stage seconds sum {total:.6f} "
                          f"exceeds wall {float(wall):.6f}")
        t_arr, t_done = row.get("t_arrival"), row.get("t_done")
        for key, v in (("t_arrival", t_arr), ("t_done", t_done)):
            if not isinstance(v, (int, float)) or not math.isfinite(v):
                errors.append(f"line {ln}: bad {key} {v!r}")
        if isinstance(t_arr, (int, float)) \
                and isinstance(t_done, (int, float)):
            if t_done < t_arr - 1e-3:
                errors.append(f"line {ln}: t_done {t_done} precedes "
                              f"t_arrival {t_arr}")
            if prev_done is not None and t_done <= prev_done:
                errors.append(f"line {ln}: t_done {t_done} is not "
                              f"strictly after the previous row's "
                              f"{prev_done} (file-order monotonicity)")
            prev_done = t_done
        batch = row.get("batch")
        if batch is not None:
            if not isinstance(batch, dict):
                errors.append(f"line {ln}: batch is not an object")
                continue
            width = batch.get("width")
            members = batch.get("members")
            if not isinstance(width, int) or width < 1:
                errors.append(f"line {ln}: bad batch width {width!r}")
            if not isinstance(members, list) or not members:
                errors.append(f"line {ln}: batch has no members list")
            else:
                if isinstance(width, int) and len(members) != width:
                    errors.append(f"line {ln}: batch width {width} != "
                                  f"{len(members)} member(s)")
                for m in members:
                    if str(m) not in all_ids:
                        errors.append(f"line {ln}: batch member {m!r} "
                                      f"references no request_id in "
                                      f"this ledger")
            solve_s = batch.get("solve_seconds")
            share = batch.get("rhs_solve_seconds")
            if isinstance(width, int) \
                    and isinstance(solve_s, (int, float)) \
                    and isinstance(share, (int, float)):
                if abs(share * width - solve_s) \
                        > 1e-3 + 1e-2 * abs(solve_s):
                    errors.append(
                        f"line {ln}: rhs_solve_seconds {share} x "
                        f"width {width} != solve_seconds {solve_s}")
    for want in require_outcomes:
        if want not in seen_outcomes:
            errors.append(f"required outcome {want!r} never appears")
    return errors


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate --access-log request ledgers "
                    "(acg-tpu-access/1)")
    ap.add_argument("files", nargs="+", metavar="FILE",
                    help="access-log JSONL file(s)")
    ap.add_argument("--min-rows", type=int, default=0, metavar="N",
                    help="fail unless the ledger has at least N rows")
    ap.add_argument("--require-outcome", action="append", default=[],
                    metavar="OUTCOME",
                    help="fail unless some row has this outcome "
                         "(repeatable)")
    args = ap.parse_args(argv)
    failed = False
    for path in args.files:
        try:
            rows, errors = _load_rows(path)
        except OSError as e:
            print(f"check_access_log: {path}: {e}", file=sys.stderr)
            return 2
        errors += validate(rows, min_rows=args.min_rows,
                           require_outcomes=args.require_outcome)
        if errors:
            failed = True
            for err in errors:
                print(f"check_access_log: {path}: {err}",
                      file=sys.stderr)
        else:
            outcomes = {}
            for _ln, row in rows:
                o = str(row.get("outcome"))
                outcomes[o] = outcomes.get(o, 0) + 1
            summary = ", ".join(f"{k} {v}"
                                for k, v in sorted(outcomes.items()))
            print(f"check_access_log: {path}: OK ({len(rows)} "
                  f"row(s): {summary or 'empty'})")
    return 1 if failed else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # stdout consumer (head, grep -m) closed early -- the cli.py
        # SIGPIPE recipe: point the fd at devnull so the interpreter's
        # exit flush cannot print a traceback after a clean verdict
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
