#!/bin/bash
# Round-5 capture queue: wait for the tunneled backend to come back,
# then run the round's measurement set in priority order, one step at a
# time (the capture discipline: no concurrent host/TPU load), each step
# timeboxed and logged, continuing past failures.
#
# Usage: scripts/r5_capture.sh [LOGDIR]
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/r5_capture}
mkdir -p "$LOG"
# wait_backend just proved the backend alive before every step; skip
# bench.py's own (redundant, full-backend-init) probe child
export ACG_TPU_SKIP_BACKEND_PROBE=1

probe() {
  timeout 120 python -c "
import jax, jax.numpy as jnp
jax.devices(); print(float(jnp.sum(jnp.ones(8))))" >/dev/null 2>&1
}

wait_backend() {
  while ! probe; do
    echo "# $(date -u +%H:%M:%S) backend unavailable; napping 300s" >&2
    sleep 300
  done
  echo "# $(date -u +%H:%M:%S) backend up" >&2
}

step() {  # step NAME TIMEOUT CMD...
  local name=$1 tmo=$2; shift 2
  wait_backend
  echo "# $(date -u +%H:%M:%S) step $name" >&2
  timeout "$tmo" "$@" > "$LOG/$name.log" 2>&1
  echo "# $(date -u +%H:%M:%S) step $name rc=$?" >&2
}

# 1. on-chip proof of the dist1 parity fix + re-measured ratio
step diag_dist1 1800 python -u scripts/diag_dist1.py
step ab_dist1   2400 python -u scripts/r5_ab.py --only dist1 --pairs 3
# 2. the open tier verdicts
step ab_bell    2400 python -u scripts/r5_ab.py --only bell --pairs 3
step ab_mixed3d 2400 python -u scripts/r5_ab.py --only mixed3d --pairs 3
step ab_planes  2400 python -u scripts/r5_ab.py --only planes3d --pairs 3
step ab_roll3d  2400 python -u scripts/r5_ab.py --only roll3d --pairs 3
step ab_proll   2400 python -u scripts/r5_ab.py --only proll --pairs 3
step ab_big     4800 python -u scripts/r5_ab.py --only mixed3d,roll3d,proll \
  --pairs 2 --big
# 3. flagship capture (probe-gated internally) + full ladder
step flagship   2400 python -u bench.py
step ladder     99999 bash scripts/ladder.sh LADDER_r05.jsonl
# 4. the quiet-window fused adjudication sweep (exit 3 = contended; the
#    hunt loop keeps trying for an honest window afterwards)
step quiet_ab   3600 python -u scripts/quiet_ab.py --min-bw 600 --pairs 3 \
  --wait-budget 600
echo "# r5 capture queue complete" >&2
