#!/usr/bin/env python3
"""Tail-latency attribution over ``--access-log`` request ledgers.

Reads one or more ``acg-tpu-access/1`` JSONL files and answers the
question the solver service's aggregate histograms cannot: when the
p99 is bad, WHERE did those requests spend their time?  Stdlib only
(the bare-pod-VM contract of the check_*/plot_* script family).

Output:

* the per-stage latency table -- count, p50/p95/p99 and the worst
  observation for every stage plus the end-to-end wall;
* outcome counts (ok / shed-* / deadline-expired / request-failed /
  invalid-request);
* the tail decomposition: the slowest 5% of requests by wall time,
  attributed stage by stage next to the overall average -- a
  queue-dominated tail (scale out / shed earlier) reads differently
  from a solve- or compile-dominated one (cache churn, cold
  programs);
* ``--fail-on-p99 SECS``: exit 7 when the wall p99 exceeds the
  budget -- the CI latency gate.

Exit codes: 0 = report printed, 1 = no usable rows, 2 = unreadable
file, 7 = p99 over the ``--fail-on-p99`` budget.

Usage:
    python scripts/access_report.py access.jsonl [more.jsonl ...] \
        [--fail-on-p99 0.5] [--tail-fraction 0.05]
"""

from __future__ import annotations

import argparse
import json
import math
import sys

SCHEMA_PREFIX = "acg-tpu-access"
STAGES = ("admit", "queue-wait", "coalesce", "cache", "compile",
          "solve", "demux", "respond")


def load_rows(paths) -> list:
    rows = []
    for path in paths:
        with open(path) as f:
            for raw in f:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    obj = json.loads(raw)
                except ValueError:
                    continue
                if isinstance(obj, dict) and str(
                        obj.get("schema", "")).startswith(SCHEMA_PREFIX):
                    rows.append(obj)
    return rows


def percentile(values, q: float):
    """Rank interpolation over a sorted copy (the estimator every
    report in this repo uses for sample percentiles)."""
    vals = sorted(v for v in values
                  if isinstance(v, (int, float)) and math.isfinite(v))
    if not vals:
        return None
    if len(vals) == 1:
        return float(vals[0])
    rank = q * (len(vals) - 1)
    lo = int(math.floor(rank))
    hi = min(lo + 1, len(vals) - 1)
    frac = rank - lo
    return float(vals[lo] * (1.0 - frac) + vals[hi] * frac)


def _fmt_s(v) -> str:
    if v is None:
        return "-"
    v = float(v)
    return f"{v * 1e3:.3g} ms" if v < 1.0 else f"{v:.3g} s"


def stage_table(rows) -> list:
    """``[(name, count, p50, p95, p99, max), ...]`` -- stages in
    service order, then the end-to-end wall."""
    out = []
    for name in STAGES:
        vals = [r["stages"][name] for r in rows
                if isinstance(r.get("stages"), dict)
                and name in r["stages"]]
        if not vals:
            continue
        out.append((name, len(vals), percentile(vals, 0.5),
                    percentile(vals, 0.95), percentile(vals, 0.99),
                    max(vals)))
    walls = [r["wall_seconds"] for r in rows
             if isinstance(r.get("wall_seconds"), (int, float))]
    if walls:
        out.append(("wall", len(walls), percentile(walls, 0.5),
                    percentile(walls, 0.95), percentile(walls, 0.99),
                    max(walls)))
    return out


def tail_decomposition(rows, fraction: float = 0.05) -> dict | None:
    """Average per-stage share of wall time, overall vs the slowest
    ``fraction`` of requests -- the queue-wait-vs-solve attribution
    of the tail."""
    timed = [r for r in rows
             if isinstance(r.get("wall_seconds"), (int, float))
             and r["wall_seconds"] > 0
             and isinstance(r.get("stages"), dict)]
    if not timed:
        return None
    timed.sort(key=lambda r: r["wall_seconds"])
    ntail = max(int(len(timed) * fraction), 1)
    tail = timed[-ntail:]

    def shares(group):
        acc = {name: 0.0 for name in STAGES}
        other = 0.0
        for r in group:
            wall = float(r["wall_seconds"])
            accounted = 0.0
            for name in STAGES:
                sec = r["stages"].get(name)
                if isinstance(sec, (int, float)) and sec > 0:
                    acc[name] += sec / wall
                    accounted += sec
            other += max(wall - accounted, 0.0) / wall
        n = len(group)
        out = {name: acc[name] / n for name in STAGES
               if acc[name] > 0}
        out["(unattributed)"] = other / n
        return out

    return {"ntail": ntail, "fraction": fraction,
            "tail_wall_min": tail[0]["wall_seconds"],
            "tail": shares(tail), "overall": shares(timed)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-stage latency percentiles and tail "
                    "attribution from --access-log ledgers")
    ap.add_argument("files", nargs="+", metavar="FILE",
                    help="acg-tpu-access/1 JSONL file(s)")
    ap.add_argument("--fail-on-p99", type=float, default=None,
                    metavar="SECS",
                    help="exit 7 when the wall p99 exceeds SECS "
                         "(the CI latency gate)")
    ap.add_argument("--tail-fraction", type=float, default=0.05,
                    metavar="F",
                    help="slowest fraction of requests to decompose "
                         "(default: 0.05)")
    args = ap.parse_args(argv)
    try:
        rows = load_rows(args.files)
    except OSError as e:
        print(f"access_report: {e}", file=sys.stderr)
        return 2
    if not rows:
        print("access_report: no acg-tpu-access rows in "
              f"{', '.join(args.files)}", file=sys.stderr)
        return 1

    outcomes = {}
    for r in rows:
        o = str(r.get("outcome"))
        outcomes[o] = outcomes.get(o, 0) + 1
    print(f"access_report: {len(rows)} request(s) from "
          f"{len(args.files)} ledger(s)")
    print("outcomes: "
          + "  ".join(f"{k} {v}" for k, v in sorted(outcomes.items())))

    print(f"{'stage':<12} {'count':>6} {'p50':>10} {'p95':>10} "
          f"{'p99':>10} {'max':>10}")
    wall_p99 = None
    for name, count, p50, p95, p99, worst in stage_table(rows):
        if name == "wall":
            wall_p99 = p99
        print(f"{name:<12} {count:>6} {_fmt_s(p50):>10} "
              f"{_fmt_s(p95):>10} {_fmt_s(p99):>10} "
              f"{_fmt_s(worst):>10}")

    decomp = tail_decomposition(rows, args.tail_fraction)
    if decomp:
        print(f"tail decomposition (slowest {decomp['ntail']} "
              f"request(s), wall >= "
              f"{_fmt_s(decomp['tail_wall_min'])}):")
        keys = [k for k in list(STAGES) + ["(unattributed)"]
                if k in decomp["tail"] or k in decomp["overall"]]
        for k in keys:
            t = decomp["tail"].get(k, 0.0)
            o = decomp["overall"].get(k, 0.0)
            print(f"  {k:<16} tail {t * 100:5.1f}%   overall "
                  f"{o * 100:5.1f}%")

    if args.fail_on_p99 is not None and wall_p99 is not None \
            and wall_p99 > args.fail_on_p99:
        print(f"access_report: wall p99 {wall_p99:.6f} s exceeds the "
              f"--fail-on-p99 budget {args.fail_on_p99:.6f} s",
              file=sys.stderr)
        return 7
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # stdout consumer (head, grep -m) closed early -- the cli.py
        # SIGPIPE recipe: point the fd at devnull so the interpreter's
        # exit flush cannot print a traceback after a clean verdict
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
