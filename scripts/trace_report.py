#!/usr/bin/env python3
"""Summarise a ``--trace`` capture dir or a ``--timeline`` file.

The role of eyeballing an nsys timeline, as a table:

* a ``--trace`` DIRECTORY (jax.profiler capture) is parsed by
  :func:`acg_tpu.tracing.analyze_trace` into measured per-op-class
  device seconds, the overlap-efficiency score (collective time hidden
  under compute vs exposed), per-phase seconds, and the cross-rank
  straggler attribution;
* a ``--timeline`` FILE (Chrome trace-event JSON from
  acg_tpu.tracing.export_chrome_trace) is summarised per part: span
  counts and per-name seconds, clock-alignment skew, event pins.

Input kind is sniffed from the filesystem (directory vs file), the
same content-over-extension discipline as plot_convergence.py.

Usage:
    python scripts/trace_report.py /tmp/acg-trace-dir
    python scripts/trace_report.py timeline.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def report_capture(path: str) -> int:
    from acg_tpu import tracing

    an = tracing.analyze_trace(path)
    print(f"trace capture: {path}")
    for line in tracing.format_analysis(an):
        print(line)
    if not an.get("available"):
        return 1
    kinds = an.get("collective_kind_seconds_in_solve") or {}
    if kinds:
        print("  collectives by kind (solve windows): "
              + ", ".join(f"{k} {v:.6f}s" for k, v in kinds.items()))
    per_rank = an.get("per_rank", [])
    if len(per_rank) > 1:
        print("  per-rank phase seconds:")
        for r in per_rank:
            ph = ", ".join(f"{k} {v:.3f}s"
                           for k, v in r.get("phase_seconds",
                                             {}).items())
            print(f"    {r['rank']}: {ph or '(no phase brackets)'} "
                  f"[busy {r.get('busy_seconds', 0.0):.3f}s]")
    return 0


def report_timeline(path: str) -> int:
    from acg_tpu import tracing

    doc = tracing.read_timeline(path)
    md = doc.get("metadata", {})
    events = doc["traceEvents"]
    pid_names: dict[int, str] = {}
    spans = defaultdict(lambda: defaultdict(float))   # pid -> name -> s
    counts: dict[int, int] = defaultdict(int)
    instants: dict[int, int] = defaultdict(int)
    t_max = 0.0
    for e in events:
        if not isinstance(e, dict):
            continue
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_names[e.get("pid")] = e.get("args", {}).get("name", "")
        elif e.get("ph") == "X":
            pid = e.get("pid")
            spans[pid][e.get("name", "?")] += e.get("dur", 0.0) * 1e-6
            counts[pid] += 1
            t_max = max(t_max, (e.get("ts", 0.0)
                                + e.get("dur", 0.0)) * 1e-6)
        elif e.get("ph") in ("i", "I"):
            instants[e.get("pid")] += 1
    clock = md.get("clock", {})
    print(f"timeline: {path} ({md.get('schema', 'unknown schema')})")
    print(f"  {md.get('nparts', len(spans))} part(s), "
          f"{md.get('nranks', 1)} rank(s), span {t_max:.3f} s, "
          f"clock max skew {clock.get('max_skew_s', 0.0):.6f} s"
          + (" (aligned)" if clock.get("aligned") else ""))
    for pid in sorted(spans):
        label = pid_names.get(pid, f"pid {pid}")
        body = ", ".join(f"{name} {secs:.3f}s"
                         for name, secs in sorted(spans[pid].items()))
        pins = (f", {instants[pid]} event pin(s)"
                if instants.get(pid) else "")
        print(f"  {label}: {counts[pid]} span(s): {body}{pins}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="summarise a --trace capture dir or a --timeline "
                    "file")
    ap.add_argument("path", help="jax.profiler capture directory, or "
                                 "Chrome trace-event timeline file")
    args = ap.parse_args(argv)
    if os.path.isdir(args.path):
        return report_capture(args.path)
    try:
        return report_timeline(args.path)
    except (OSError, ValueError) as e:
        print(f"trace_report: {args.path}: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # stdout consumer (head, grep -m) closed early -- the cli.py
        # SIGPIPE recipe: point the fd at devnull so the interpreter's
        # exit flush cannot print a traceback after a clean summary
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
