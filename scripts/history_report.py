#!/usr/bin/env python3
"""Render per-case trend tables from a ``--history`` run ledger.

The enforced form of eyeballing BENCH rounds across time: point this at
the date-partitioned JSONL ledger ``--history DIR`` maintains
(acg-tpu-history/1 index lines, one per solve) and get, per case key,
how latency and iterations moved across every recorded run --
first/last/best, an EWMA latency trail (the soak drift detector's
estimator applied across RUNS instead of within one), and a DRIFT flag
when the EWMA ends more than the threshold above the early-runs
baseline (median of the leading window, so one slow first run cannot
poison it).

Captures recording only the ``bench_backend_unavailable`` sentinel are
listed (they are history) but never enter the trend math.

Planned solves (``--autotune``) carry a plan-vs-actual row in their
stats twin; those cases grow a plan column -- predicted/measured ratio
first -> last -- and ``--fail-on-misprediction PCT`` turns it into a
CI gate for cost-model drift.

Usage:
    python scripts/history_report.py DIR [--threshold PCT]
        [--fail-on-drift] [--fail-on-misprediction PCT]

Exit codes: 0 = report printed, 1 = unreadable/empty ledger, and with
``--fail-on-drift`` / ``--fail-on-misprediction``: 7 when any case's
latency EWMA drifted past the threshold, or any case's latest
predicted/measured ratio strayed more than PCT from 1.0 (the soak
gate's exit code -- one contract for all the drift gates).
"""

from __future__ import annotations

import argparse
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the soak drift detector's constants, applied across runs
EWMA_ALPHA = 0.2
BASELINE_MIN = 3
BASELINE_FRACTION = 0.2
DEFAULT_THRESHOLD_PCT = 50.0
DRIFT_EXIT_CODE = 7


def case_trend(entries: list[dict], threshold_pct: float) -> dict:
    """Trend statistics for one case's chronologically-sorted ledger
    entries: latency first/last/best/EWMA + slope, iteration
    first/last, and the drift verdict."""
    lats = [(e.get("latency_s"), e) for e in entries]
    lats = [(float(v), e) for v, e in lats
            if isinstance(v, (int, float)) and math.isfinite(v)
            and v > 0]
    out: dict = {"runs": len(entries), "timed_runs": len(lats)}
    its = [e.get("iterations") for e in entries
           if isinstance(e.get("iterations"), (int, float))]
    if its:
        out["iterations"] = {"first": int(its[0]), "last": int(its[-1]),
                             "min": int(min(its)), "max": int(max(its))}
    if not lats:
        return out
    vals = [v for v, _ in lats]
    nbase = max(BASELINE_MIN, int(len(vals) * BASELINE_FRACTION))
    window = sorted(vals[:nbase])
    baseline = window[len(window) // 2]
    # plan-vs-actual trail: planned solves record predicted/measured
    # into stats.plan (acg_tpu.planner); unplanned runs have no row
    ratios = []
    for e in entries:
        plan = ((e.get("doc") or {}).get("stats") or {}).get("plan")
        r = (plan or {}).get("misprediction_ratio")
        if isinstance(r, (int, float)) and math.isfinite(r) and r > 0:
            ratios.append(float(r))
    if ratios:
        out["plan"] = {"planned_runs": len(ratios),
                       "first": ratios[0], "last": ratios[-1]}
    ewma = vals[0]
    for v in vals[1:]:
        ewma = (1.0 - EWMA_ALPHA) * ewma + EWMA_ALPHA * v
    ratio = (ewma / baseline) if baseline > 0 else 1.0
    out["latency"] = {
        "first": vals[0], "last": vals[-1], "best": min(vals),
        "worst": max(vals), "ewma": ewma, "baseline": baseline,
        "ratio": ratio,
        # per-run EWMA slope over the trail: sign says which way the
        # case is moving even before the drift gate trips
        "ewma_slope_per_run": ((ewma - baseline) / max(len(vals) - 1, 1)
                               if baseline > 0 else 0.0),
    }
    # the gate inspects nothing when the baseline window consumes the
    # whole trail (the soak gate_is_vacuous rule)
    out["drift"] = (len(vals) > nbase and baseline > 0
                    and ratio > 1.0 + threshold_pct / 100.0)
    return out


def _fmt_s(v: float) -> str:
    return f"{v * 1e3:.3g}ms" if v < 1.0 else f"{v:.4g}s"


def render(cases: dict, threshold_pct: float,
           misprediction_pct: float | None = None,
           ) -> tuple[list[str], bool, bool]:
    lines: list[str] = []
    any_drift = any_mispredict = False
    for case in sorted(cases):
        t = cases[case]
        head = f"{case}: {t['runs']} run(s)"
        lat = t.get("latency")
        if lat:
            head += (f"  latency first {_fmt_s(lat['first'])} -> last "
                     f"{_fmt_s(lat['last'])} (best {_fmt_s(lat['best'])}"
                     f", EWMA {_fmt_s(lat['ewma'])}, "
                     f"x{lat['ratio']:.2f} vs baseline)")
        it = t.get("iterations")
        if it:
            head += (f"  iters {it['first']} -> {it['last']}"
                     + (f" (max {it['max']})"
                        if it["max"] != it["last"] else ""))
        plan = t.get("plan")
        if plan:
            head += (f"  plan x{plan['first']:.2f} -> x{plan['last']:.2f}"
                     f" ({plan['planned_runs']} planned)")
        else:
            head += "  plan -"
        if t.get("drift"):
            any_drift = True
            head += (f"  DRIFT (> +{threshold_pct:g}% over the "
                     f"early-runs baseline)")
        if (plan and misprediction_pct is not None
                and abs(plan["last"] - 1.0) * 100.0 > misprediction_pct):
            any_mispredict = True
            head += (f"  MISPREDICTION (latest predicted/measured "
                     f"x{plan['last']:.2f} strays > {misprediction_pct:g}% "
                     f"from 1.0)")
        lines.append(head)
    return lines, any_drift, any_mispredict


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="history_report.py",
        description="per-case latency/iteration trend tables over a "
                    "--history run ledger, with the soak drift "
                    "detector's EWMA applied across runs")
    ap.add_argument("history", metavar="DIR",
                    help="the --history ledger directory")
    ap.add_argument("--threshold", type=float,
                    default=DEFAULT_THRESHOLD_PCT, metavar="PCT",
                    help="drift flag threshold in percent over the "
                         "early-runs baseline (default: 50)")
    ap.add_argument("--fail-on-drift", action="store_true",
                    help="exit 7 (the soak drift gate's code) when any "
                         "case drifted past the threshold")
    ap.add_argument("--fail-on-misprediction", type=float, default=None,
                    metavar="PCT",
                    help="exit 7 when any case's latest plan "
                         "predicted/measured ratio strays more than PCT "
                         "percent from 1.0 (cost-model drift gate)")
    args = ap.parse_args(argv)

    from acg_tpu.observatory import history_scan
    from acg_tpu.perfmodel import UNAVAILABLE_METRIC

    entries = history_scan(args.history)
    if not entries:
        print(f"history-report: {args.history}: no ledger entries "
              f"(not a --history directory?)", file=sys.stderr)
        return 1
    by_case: dict[str, list] = {}
    nunavail = 0
    for e in entries:
        case = e.get("case") or "(uncased)"
        if str(case).startswith(UNAVAILABLE_METRIC):
            nunavail += 1
            continue
        by_case.setdefault(str(case), []).append(e)
    trends = {case: case_trend(es, args.threshold)
              for case, es in by_case.items()}
    lines, any_drift, any_mispredict = render(
        trends, args.threshold,
        misprediction_pct=args.fail_on_misprediction)
    for ln in lines:
        print(ln)
    tail = (f"history-report: {len(entries)} entr"
            f"{'y' if len(entries) == 1 else 'ies'} over "
            f"{len(by_case)} case(s)")
    if nunavail:
        tail += (f"; {nunavail} backend-unavailable capture(s) "
                 f"excluded from trends")
    print(tail)
    if any_drift and args.fail_on_drift:
        return DRIFT_EXIT_CODE
    if any_mispredict:
        return DRIFT_EXIT_CODE
    return 0


if __name__ == "__main__":
    sys.exit(main())
