"""Microbenchmark: Pallas kernel tier vs XLA fusion on the live device.

Measures the two hand-written kernels (ops/pallas_kernels.py) against
their XLA formulations on the flagship workload shapes (poisson2d n=2048:
N=4,194,304, 5 diagonals), plus the end-to-end flagship solve with
--kernels pallas vs xla.  Records go to BASELINE.md.

Run: python scripts/bench_pallas.py  (TPU; off-TPU it measures interpret
mode, which is meaningless for performance)
"""

from __future__ import annotations

import sys
import time

import numpy as np


def timeit(f, *a, reps=50):
    r = f(*a)
    (r[0] if isinstance(r, tuple) else r).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(reps):
        r = f(*a)
    (r[0] if isinstance(r, tuple) else r).block_until_ready()
    return (time.perf_counter() - t0) / reps * 1e6


def main() -> int:
    import jax
    import jax.numpy as jnp

    from acg_tpu.ops.pallas_kernels import dia_spmv, fused_pipelined_update
    from acg_tpu.ops.spmv import dia_mv

    print(f"# platform: {jax.devices()[0].platform}", file=sys.stderr)
    rng = np.random.default_rng(0)
    n = 2048 * 2048
    offsets = (-2048, -1, 0, 1, 2048)
    planes = tuple(jnp.asarray(rng.standard_normal(n), jnp.float32)
                   for _ in offsets)
    x = jnp.asarray(rng.standard_normal(n), jnp.float32)

    xla_mv = jax.jit(lambda pls, xs: dia_mv(pls, offsets, n, xs))
    t_xla = timeit(xla_mv, planes, x)
    t_pal = timeit(lambda pls, xs: dia_spmv(pls, offsets, xs), planes, x)
    print(f"spmv_dia_n{n}: xla {t_xla:.1f} us, pallas {t_pal:.1f} us "
          f"({t_xla / t_pal:.2f}x)")

    vs = [jnp.asarray(rng.standard_normal(n), jnp.float32) for _ in range(7)]
    a, b = jnp.float32(0.3), jnp.float32(0.7)

    @jax.jit
    def xla_update(x0, r0, w0, p0, t0, z0, q0, a, b):
        zn = q0 + b * z0
        tn = w0 + b * t0
        pn = r0 + b * p0
        return (x0 + a * pn, r0 - a * tn, w0 - a * zn, pn, tn, zn)

    t_xla = timeit(xla_update, *vs, a, b)
    t_pal = timeit(lambda *args: fused_pipelined_update(*args), *vs, a, b)
    print(f"pipelined_update_n{n}: xla {t_xla:.1f} us, pallas {t_pal:.1f} us "
          f"({t_xla / t_pal:.2f}x)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
