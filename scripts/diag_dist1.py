"""Bisect the nparts=1 distributed-program slowdown (VERDICT r4 item 4;
reproduced round 5 same-window: dist1 0.036x of the single-chip solver).

Times stripped-down CG-shaped loops at the flagship size (n=2048^2,
5-diagonal DIA), all on one device, isolating one suspect per variant:

  single      plain jit fori: spmv(DiaMatrix) + jnp.dot      (control)
  single_dia  plain jit fori: dia_mv (the dist shard formulation)
  smap_local  shard_map(1-device): dia_mv + LOCAL dots (no psum)
  smap_psum   shard_map(1-device): dia_mv + psum dots (the PRE-FIX
              dist program shape: the 2-all-reduces-per-iteration
              pathology)
  smap_pad    shard_map(1-device): the dist layout (leading parts axis,
              stripped inside the shard), psum dots
  dist_fixed  the REAL DistCGSolver at nparts=1, post-fix: with the
              commsize==1 parity bypass (parallel/dist.py) it should
              time within noise of `single` -- the fix's on-chip proof

Per-iteration rate comes from the (400 - 100)-iteration difference of
two program sizes, so the broken-completion-signal dispatch round-trip
cancels (bench two-point rationale).  One JSON line per variant.
"""

from __future__ import annotations

import functools
import json
import sys
import time

ROOT = __file__.rsplit("/", 2)[0]
sys.path.insert(0, ROOT)


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from acg_tpu._platform import shard_map as _shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from acg_tpu._platform import (device_sync, enable_compile_cache,
                                   honour_jax_platforms)
    from acg_tpu.io.generators import poisson2d_coo, poisson_dia_device
    from acg_tpu.matrix import SymCsrMatrix
    from acg_tpu.ops.spmv import DiaMatrix, dia_mv, spmv
    from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
    from acg_tpu.parallel.mesh import PARTS_AXIS, solve_mesh
    from acg_tpu.partition import partition_rows
    from acg_tpu.solvers.stats import StoppingCriteria

    honour_jax_platforms()  # JAX_PLATFORMS=cpu debug runs stay CPU
    enable_compile_cache()
    n = 2048
    planes, offsets, N = poisson_dia_device(n, 2, dtype=jnp.float32)
    A = DiaMatrix(data=tuple(planes), offsets=offsets,
                  nrows=N, ncols_padded=N)
    b = jnp.ones(N, jnp.float32)
    mesh = solve_mesh(1)

    def cg_loop(spmv_fn, dot_fn, b, its):
        x = jnp.zeros_like(b)
        r = b
        p = r
        gamma = dot_fn(r, r)

        def body(_, st):
            x, r, p, gamma = st
            t = spmv_fn(p)
            alpha = gamma / dot_fn(p, t)
            x = x + alpha * p
            r = r - alpha * t
            g2 = dot_fn(r, r)
            p = r + (g2 / gamma) * p
            return (x, r, p, g2)

        return lax.fori_loop(0, its, body, (x, r, p, gamma))[0]

    fdot = lambda a, c: jnp.dot(a, c)  # noqa: E731
    pdot = lambda a, c: lax.psum(jnp.dot(a, c), PARTS_AXIS)  # noqa: E731

    sh = NamedSharding(mesh, P(PARTS_AXIS))
    planes_sh = tuple(jax.device_put(p, sh) for p in A.data)
    b_sh = jax.device_put(b, sh)
    planes_st = tuple(jax.device_put(jnp.asarray(p)[None], sh)
                      for p in A.data)
    b_st = jax.device_put(b[None], sh)

    def make(variant):
        if variant == "single":
            @functools.partial(jax.jit, static_argnames="its")
            def prog(planes, b, its):
                Ad = DiaMatrix(data=planes, offsets=offsets,
                               nrows=N, ncols_padded=N)
                return cg_loop(lambda v: spmv(Ad, v), fdot, b, its)
            return lambda its: device_sync(prog(A.data, b, its))
        if variant == "single_dia":
            @functools.partial(jax.jit, static_argnames="its")
            def prog(planes, b, its):
                return cg_loop(lambda v: dia_mv(planes, offsets, N, v),
                               fdot, b, its)
            return lambda its: device_sync(prog(A.data, b, its))
        if variant in ("smap_local", "smap_psum"):
            dot = fdot if variant == "smap_local" else pdot

            @functools.partial(jax.jit, static_argnames="its")
            def prog(planes, b, its):
                return _shard_map(
                    lambda p_, b_: cg_loop(
                        lambda v: dia_mv(p_, offsets, N, v), dot, b_, its),
                    mesh=mesh, in_specs=(P(PARTS_AXIS), P(PARTS_AXIS)),
                    out_specs=P(PARTS_AXIS))(planes, b)
            return lambda its: device_sync(prog(planes_sh, b_sh, its))
        if variant == "smap_pad":
            def shard(p_, b_, its):
                p_ = tuple(q[0] for q in p_)
                y = cg_loop(lambda v: dia_mv(p_, offsets, N, v),
                            pdot, b_[0], its)
                return y[None]

            @functools.partial(jax.jit, static_argnames="its")
            def prog(planes, b, its):
                return _shard_map(
                    functools.partial(shard, its=its),
                    mesh=mesh, in_specs=(P(PARTS_AXIS), P(PARTS_AXIS)),
                    out_specs=P(PARTS_AXIS))(planes, b)
            return lambda its: device_sync(prog(planes_st, b_st, its))
        if variant == "dist_fixed":
            rr, cc, vv, _ = poisson2d_coo(n)
            csr = SymCsrMatrix.from_coo(N, rr, cc, vv).to_csr()
            part = partition_rows(csr, 1, seed=0)
            prob = DistributedProblem.build(csr, part, 1,
                                            dtype=jnp.float32)
            solver = DistCGSolver(prob, kernels="xla")
            b_host = np.ones(N, np.float32)

            def run(its):
                # solve() device_syncs its result internally
                solver.solve(b_host,
                             criteria=StoppingCriteria(maxits=its),
                             host_result=False)
            return run
        raise ValueError(variant)

    for name in ("single", "single_dia", "smap_local", "smap_psum",
                 "smap_pad", "dist_fixed"):
        run = make(name)

        def timed(its, run=run):
            run(its)  # compile + warm
            ts = []
            for _ in range(3):
                t0 = time.perf_counter()
                run(its)
                ts.append(time.perf_counter() - t0)
            return min(ts)

        t100, t400 = timed(100), timed(400)
        dt = t400 - t100
        rate = 300.0 / dt if dt > 0 else float("nan")
        print(json.dumps({"variant": name,
                          "iters_per_sec": round(rate, 1),
                          "t100": round(t100, 4), "t400": round(t400, 4)}))
        sys.stdout.flush()
    return 0


if __name__ == "__main__":
    sys.exit(main())
