"""Quiet-window kernel A/B: re-evaluate the fused-kernel verdicts.

Round 2 retired the fused SpMV+dot and fused 6-vector-update kernels on
in-loop A/Bs taken in CONTENDED windows (BASELINE.md); the round-2
verdict asked for a probe-gated re-run.  This script refuses to measure
unless the bandwidth probe confirms a quiet window (>= --min-bw GB/s,
default 600: quiet v5e probes ~800-915), then runs interleaved
whole-solve A/Bs on the flagship config:

  * classic CG: pallas dia_spmv tier vs xla tier
  * classic CG: fused dia_spmv_dot in-loop vs pallas-SpMV + XLA dot
  * classic CG: the two-phase fused iteration (kernels="fused"), f32
    and mixed, vs the xla tier -- the verdict BASELINE.md defers to
    this harness
  * pipelined CG: fused 6-vector pallas update vs XLA fusion
  * storage tiers: f32 vs mixed vs bf16 (xla tier)
  * the sound-bf16 tier (replace_every=50 residual replacement) vs
    plain bf16 and vs f32 (round 4)

Exit 3 = window contended, nothing measured.  Results print as JSON
lines AND append to QUIET_AB.jsonl at the repo root (with a probe
reading and timestamp per row) -- the quiet-window record the round-3
verdict asked for.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

ROOT = __file__.rsplit("/", 2)[0]
sys.path.insert(0, ROOT)
RECORD = os.path.join(ROOT, "QUIET_AB.jsonl")


def _flagship():
    import jax.numpy as jnp

    from acg_tpu.io.generators import poisson2d_coo
    from acg_tpu.matrix import SymCsrMatrix
    from acg_tpu.ops.spmv import device_matrix_from_csr

    r, c, v, N = poisson2d_coo(2048)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    return {
        "f32": device_matrix_from_csr(csr, dtype=jnp.float32),
        "bf16": device_matrix_from_csr(csr, dtype=jnp.bfloat16),
    }, csr.shape[0]


def _time_case(make_solver, b, its=1000, reps=3):
    import numpy as np

    from acg_tpu._platform import block_until_ready_works
    from acg_tpu.solvers.stats import StoppingCriteria

    s = make_solver()

    def timed(n):
        s.stats.tsolve = 0.0
        s.solve(b, criteria=StoppingCriteria(maxits=n))
        return s.stats.tsolve

    timed(50)
    timed(50)
    best = min(timed(its) for _ in range(reps))
    if not block_until_ready_works():
        # fetch-sync timing: subtract the dispatch round-trip via a
        # second point (bench._time_solver rationale)
        t_short = min(timed(its // 4) for _ in range(reps))
        dt = best - t_short
        if dt > 0 and best / (dt / (its - its // 4) * its) < 20:
            best = dt / (its - its // 4) * its
    return its / best


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--min-bw", type=float, default=600.0,
                    help="GB/s probe threshold for a quiet window")
    ap.add_argument("--pairs", type=int, default=4,
                    help="interleaved A/B pairs per comparison")
    ap.add_argument("--wait-budget", type=float, default=300.0,
                    help="seconds to keep re-probing for a quiet window "
                         "before giving up (exit 3)")
    args = ap.parse_args(argv)

    from acg_tpu._platform import enable_compile_cache
    enable_compile_cache()
    import numpy as np

    from bench import bandwidth_probe_gbs, wait_for_quiet
    bw, quiet = wait_for_quiet(budget_s=args.wait_budget,
                               min_bw=args.min_bw)
    print(f"# probe: {bw:.0f} GB/s", file=sys.stderr)
    if not quiet:
        print(json.dumps({"quiet": False, "bw_gbs": round(bw, 1)}))
        return 3

    from acg_tpu.solvers.jax_cg import JaxCGSolver

    As, N = _flagship()
    b = np.ones(N, dtype=np.float32)

    def ab(name, mk_a, mk_b, label_a, label_b):
        va, vb = [], []
        for _ in range(args.pairs):
            va.append(_time_case(mk_a, b, reps=1))
            vb.append(_time_case(mk_b, b, reps=1))
        ra, rb = float(np.median(va)), float(np.median(vb))
        bw2 = bandwidth_probe_gbs(refresh=True)
        row = {"ab": name, label_a: round(ra, 1), label_b: round(rb, 1),
               "ratio": round(ra / rb, 3), "bw_gbs": round(bw, 1),
               "bw_gbs_after": round(bw2, 1), "pairs": args.pairs,
               "ts": round(time.time(), 1)}
        from acg_tpu._platform import block_until_ready_works
        if not block_until_ready_works():
            row["block_sync_broken"] = True
        print(json.dumps(row))
        sys.stdout.flush()
        with open(RECORD, "a") as f:
            f.write(json.dumps(row) + "\n")

    ab("pallas_vs_xla_classic",
       lambda: JaxCGSolver(As["f32"], kernels="pallas"),
       lambda: JaxCGSolver(As["f32"], kernels="xla"),
       "pallas", "xla")
    ab("mixed_vs_f32_classic",
       lambda: JaxCGSolver(As["bf16"], kernels="xla",
                           vector_dtype=np.float32),
       lambda: JaxCGSolver(As["f32"], kernels="xla"),
       "mixed", "f32")
    ab("bf16_vs_f32_classic",
       lambda: JaxCGSolver(As["bf16"], kernels="xla"),
       lambda: JaxCGSolver(As["f32"], kernels="xla"),
       "bf16", "f32")
    ab("fused_vs_xla_classic",
       lambda: JaxCGSolver(As["f32"], kernels="fused"),
       lambda: JaxCGSolver(As["f32"], kernels="xla"),
       "fused", "xla")
    ab("mixed_fused_vs_xla_classic",
       lambda: JaxCGSolver(As["bf16"], kernels="fused",
                           vector_dtype=np.float32),
       lambda: JaxCGSolver(As["f32"], kernels="xla"),
       "mixed_fused", "xla")
    ab("pipelined_pallas_update_vs_xla",
       lambda: _fused_update_solver(As["f32"]),
       lambda: JaxCGSolver(As["f32"], pipelined=True, kernels="xla"),
       "fused", "xla")
    ab("fused_spmv_dot_vs_split",
       lambda: _fused_dot_solver(As["f32"]),
       lambda: JaxCGSolver(As["f32"], kernels="pallas"),
       "fused", "split")
    # the sound-bf16 tier (periodic f32 residual replacement): its
    # overhead over plain bf16 is the price of the accuracy contract,
    # and its ratio to f32 is the headline claim
    ab("bf16rr_vs_bf16_classic",
       lambda: JaxCGSolver(As["bf16"], kernels="xla", replace_every=50),
       lambda: JaxCGSolver(As["bf16"], kernels="xla"),
       "bf16rr", "bf16")
    ab("bf16rr_vs_f32_classic",
       lambda: JaxCGSolver(As["bf16"], kernels="xla", replace_every=50),
       lambda: JaxCGSolver(As["f32"], kernels="xla"),
       "bf16rr", "f32")
    return 0


def _fused_dot_solver(A):
    """Classic CG whose (p, Ap) comes from the fused dia_spmv_dot kernel
    (the round-2 retiree, re-tried under quiet-window conditions)."""
    import functools

    import jax
    import jax.numpy as jnp

    from acg_tpu.ops.pallas_kernels import dia_spmv_dot
    from acg_tpu.solvers.stats import SolverStats

    class FusedDotSolver:
        def __init__(self, A):
            self.A = A
            self.stats = SolverStats(unknowns=A.nrows)
            offs = A.offsets

            @functools.partial(jax.jit, static_argnames=("maxits",))
            def prog(planes, b, maxits):
                x = jnp.zeros_like(b)
                r = b
                p = r
                gamma = jnp.dot(r, r)

                def body(_, st):
                    x, r, p, gamma = st
                    t, pdott = dia_spmv_dot(planes, offs, p)
                    alpha = gamma / pdott
                    x = x + alpha * p
                    r = r - alpha * t
                    gamma_next = jnp.dot(r, r)
                    p2 = r + (gamma_next / gamma) * p
                    return (x, r, p2, gamma_next)

                return jax.lax.fori_loop(0, maxits, body,
                                         (x, r, p, gamma))[0]

            self._prog = prog

        def solve(self, b, criteria=None, **kw):
            import time as _t
            b = jnp.asarray(b, self.A.dtype)
            t0 = _t.perf_counter()
            x = self._prog(tuple(self.A.data), b, criteria.maxits)
            from acg_tpu._platform import device_sync
            device_sync(x)
            self.stats.tsolve += _t.perf_counter() - t0
            return x

    return FusedDotSolver(A)


def _fused_update_solver(A):
    """Pipelined CG using the pallas fused 6-vector update in-loop."""
    import functools

    import jax
    import jax.numpy as jnp

    from acg_tpu.ops.pallas_kernels import dia_spmv, fused_pipelined_update
    from acg_tpu.solvers.stats import SolverStats

    class FusedUpdateSolver:
        def __init__(self, A):
            self.A = A
            self.stats = SolverStats(unknowns=A.nrows)
            offs = A.offsets

            @functools.partial(jax.jit, static_argnames=("maxits",))
            def prog(planes, b, maxits):
                x = jnp.zeros_like(b)
                r = b
                w = dia_spmv(planes, offs, r)
                z = t = p = jnp.zeros_like(b)
                inf = jnp.asarray(jnp.inf, b.dtype)

                def body(_, st):
                    x, r, w, p, t, z, gp, ap = st
                    gamma = jnp.dot(r, r)
                    delta = jnp.dot(w, r)
                    q = dia_spmv(planes, offs, w)
                    beta = gamma / gp
                    alpha = gamma / (delta - beta * (gamma / ap))
                    x, r, w, p, t, z = fused_pipelined_update(
                        x, r, w, p, t, z, q, alpha, beta)
                    return (x, r, w, p, t, z, gamma, alpha)

                return jax.lax.fori_loop(
                    0, maxits, body, (x, r, w, p, t, z, inf, inf))[0]

            self._prog = prog

        def solve(self, b, criteria=None, **kw):
            import time as _t
            b = jnp.asarray(b, self.A.dtype)
            t0 = _t.perf_counter()
            x = self._prog(tuple(self.A.data), b, criteria.maxits)
            from acg_tpu._platform import device_sync
            device_sync(x)
            self.stats.tsolve += _t.perf_counter() - t0
            return x

    return FusedUpdateSolver(A)


if __name__ == "__main__":
    sys.exit(main())
