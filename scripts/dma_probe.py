"""Real-hardware probe of the DMA transport's primitives (round-4
verdict item 6): pin the Mosaic lowering of ``make_async_remote_copy``
+ barrier/DMA semaphores on an actual TPU chip, even with only one chip
available (self-puts: device_id = own index).

Result record (TPU v5 lite behind the axon tunnel, 2026-07-30):

1. ``halo_dma._exchange`` compiled at nparts=1 (barrier present, put
   loops empty): remote compile helper dies with SIGABRT -- a Mosaic
   crash on the degenerate kernel.  The library now short-circuits
   nparts==1 before reaching Pallas.
2. Self-put WITHOUT a barrier but with collective_id=0: JAX rejects --
   "collective_id has to be unspecified or None when not using a
   custom barrier".
3. Self-put WITH the barrier handshake (the transport's actual
   structure): COMPILES AND RUNS, payload bit-exact.  This is the
   first on-silicon execution of the put-with-signal path; what
   remains unproven on real hardware is only the multi-chip case (no
   second chip here), which is why ``DistCGSolver`` still rejects
   ``comm='dma'`` across controllers.

Run: ``python scripts/dma_probe.py`` (needs a real TPU; CPU runs
interpret mode and proves nothing).
"""

from __future__ import annotations

import sys

import numpy as np

ROOT = __file__.rsplit("/", 2)[0]
sys.path.insert(0, ROOT)


def main() -> int:
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from jax.sharding import Mesh, PartitionSpec as P

    from acg_tpu._platform import shard_map  # version compat
    from acg_tpu.parallel.halo_dma import _compiler_params

    d = jax.devices()[0]
    print(f"# platform: {d.platform} {d.device_kind}", file=sys.stderr)
    if d.platform != "tpu":
        print("not a TPU; nothing to probe", file=sys.stderr)
        return 2
    mesh = Mesh(np.array(jax.devices()[:1]), ("parts",))

    def kernel(src_ref, dst_ref, send_sem, recv_sem):
        me = lax.axis_index("parts")
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id=me,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(barrier, 1)
        copy = pltpu.make_async_remote_copy(
            src_ref=src_ref, dst_ref=dst_ref, send_sem=send_sem,
            recv_sem=recv_sem, device_id=me,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        copy.start()
        copy.wait_send()
        copy.wait_recv()

    def selfput(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(())],
            compiler_params=_compiler_params(has_side_effects=True,
                                             collective_id=1),
            interpret=False)(x)

    f = shard_map(selfput, mesh=mesh, in_specs=P("parts"),
                  out_specs=P("parts"))
    x = jnp.arange(8 * 128, dtype=jnp.float32).reshape(1, 8, 128)
    out = jax.jit(f)(x)
    out.block_until_ready()
    ok = np.array_equal(np.asarray(out), np.asarray(x))
    print(f"barrier + self-put make_async_remote_copy: compiled and ran; "
          f"payload correct: {ok}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
