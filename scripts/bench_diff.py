#!/usr/bin/env python3
"""Diff two performance captures case-by-case and gate on regressions.

The enforced form of the ``BENCH_*.json`` trajectory: instead of
eyeballing rows across rounds, point this at any two captures and get a
per-case verdict plus a nonzero exit on regression.  Accepts EITHER
format on either side:

* ``--stats-json`` documents -- one indented document (CLI solves) or
  JSONL-appended (``bench.py --stats-json``, ``--explain``); the case
  value is iterations/second derived from the stats twin
  (``niterations / tsolve``), keyed by the manifest's metric (bench) or
  ``solver:matrix`` (CLI);
* bench summary-row JSONL (``BENCH_*.json``); the case value is the
  row's ``value``, keyed by ``metric``.  ``#`` commentary lines are
  skipped.

With ``--baseline-from-history DIR`` the baseline side comes from a
run-history ledger (``--history``, acg_tpu.observatory): the
best-known USABLE prior capture per case, with
``bench_backend_unavailable`` entries skipped automatically; a ledger
whose entries are ALL unavailable refuses with exit 2 and the
re-baseline message (the BENCH_r05 stale-baseline trap).

Exit codes (shared with ``bench.py --baseline --fail-on-regress``):
0 = no regression, 1 = at least one case regressed past the threshold,
2 = nothing comparable (unreadable input / no common cases /
all-unavailable history) -- 2 fails too, so a renamed metric cannot
silently green a CI gate.

Examples:
  bench_diff.py BENCH_r04.json BENCH_r05.json
  bench_diff.py old_stats.jsonl new_stats.jsonl --fail-on-regress 5
  bench_diff.py --baseline-from-history ./history new_stats.jsonl
"""

import argparse
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_diff.py",
        description="Diff two bench / --stats-json captures case-by-case "
                    "and exit nonzero on regression (the enforced BENCH "
                    "trajectory gate).",
        epilog="Exit codes: 0 = ok, 1 = regression past the threshold, "
               "2 = nothing comparable.")
    ap.add_argument("baseline", nargs="?", default=None,
                    help="prior capture (--stats-json JSONL/document, or "
                         "bench row JSONL like BENCH_*.json); omit when "
                         "--baseline-from-history supplies the baseline")
    ap.add_argument("candidate", help="new capture, same accepted formats")
    ap.add_argument("--baseline-from-history", metavar="DIR",
                    default=None,
                    help="take the baseline from a --history run ledger "
                         "instead of a capture file: best USABLE value "
                         "per case across every entry, "
                         "bench_backend_unavailable captures skipped; "
                         "an all-unavailable ledger refuses (exit 2)")
    ap.add_argument("--fail-on-regress", type=float, default=10.0,
                    metavar="PCT",
                    help="regression threshold in percent (default: 10)")
    args = ap.parse_args(argv)
    if (args.baseline is None) == (args.baseline_from_history is None):
        ap.error("give a baseline capture OR --baseline-from-history "
                 "DIR (exactly one)")

    # import AFTER parsing so --help answers without touching the
    # package (and never initialises a jax backend -- perfmodel keeps
    # jax imports inside the functions that need a device)
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from acg_tpu.perfmodel import (compare_cases, load_baseline_cases,
                                   load_cases, refuse_unavailable)

    base = args.baseline or args.baseline_from_history
    try:
        # a directory baseline is the run-history ledger path; the
        # positional form accepts one too (check_regression parity)
        old = load_baseline_cases(base)
        new = load_cases(args.candidate)
    except OSError as e:
        print(f"bench-diff: {e}", file=sys.stderr)
        return 2
    if old is None:
        # the ledger was empty or ALL its captures were
        # backend-unavailable: load_baseline_cases printed the
        # re-baseline refusal
        return 2
    # a capture that only records the backend-unavailable sentinel
    # (BENCH_r05-style: the tunnel was down, value 0) describes a run
    # that never reached hardware -- refuse the comparison outright
    # instead of "diffing" against nothing (ROADMAP Recent notes r05)
    old, new, refused = refuse_unavailable(old, new, base,
                                           args.candidate)
    if refused:
        return 2
    lines, nreg, ncmp = compare_cases(old, new, args.fail_on_regress)
    for ln in lines:
        print(ln)
    if ncmp == 0:
        print("bench-diff: no comparable cases between "
              f"{base} and {args.candidate}", file=sys.stderr)
        return 2
    print(f"bench-diff: {ncmp} case(s) compared, {nreg} regression(s) "
          f"past -{args.fail_on_regress:g}%")
    return 1 if nreg else 0


if __name__ == "__main__":
    sys.exit(main())
